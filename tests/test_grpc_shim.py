"""gRPC shim end-to-end: the reference's RPC surface over real gRPC.

Covers the 12 reference RPC methods (server/server.go:19-251) plus the
membership verbs, against a live grpc.Server on an ephemeral localhost port
backed by a small CoSim.
"""

from __future__ import annotations

import pytest

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.cosim import CoSim
from gossipfs_tpu.sdfs.types import REPLICATION_FACTOR
from gossipfs_tpu.shim.client import ShimClient
from gossipfs_tpu.shim.service import ShimServer, ShimServicer


@pytest.fixture()
def shim():
    sim = CoSim(SimConfig(n=12), seed=3)
    server = ShimServer(sim, port=0).start()
    client = ShimClient(server.address, timeout=10.0)
    yield sim, client
    client.close()
    server.stop()


def test_membership_verbs_roundtrip(shim):
    sim, client = shim
    assert client.alive_nodes() == list(range(12))
    assert client.lsm(0) == list(range(12))
    # warm up heartbeats past the hb<=1 detection grace (slave.go:468-469)
    client.advance(3)
    client.crash(5)
    # detection needs t_fail rounds plus slack for dissemination
    r = client.advance(10)
    assert r == 13
    assert 5 not in client.alive_nodes()
    assert 5 not in client.lsm(0)
    resp = client.call("Events")
    events = resp["events"]
    assert any(e["subject"] == 5 and not e["false_positive"] for e in events)
    # cursor semantics: polling from `next` returns only new events
    follow_up = client.call("Events", since=resp["next"])
    assert follow_up["events"] == []
    assert follow_up["next"] == resp["next"]


def test_put_get_delete_ls_store(shim):
    sim, client = shim
    payload = b"wikipedia dump shard" * 100
    assert client.put("file1.txt", payload)
    assert client.get("file1.txt") == payload
    replicas = client.ls("file1.txt")
    assert len(replicas) == REPLICATION_FACTOR
    listing = client.store(replicas[0])
    assert listing["file1.txt"] == 1
    assert client.delete("file1.txt")
    assert client.get("file1.txt") is None
    assert client.ls("file1.txt") == []


def test_multi_mb_payload_roundtrip(shim):
    """The reference's benchmark workload is ~4 MB files (file1-10.txt);
    a whole file must survive one Put/Get across the shim (the default
    gRPC 4 MB message cap would reject the base64-inflated payload)."""
    sim, client = shim
    import os

    payload = os.urandom(4 * 1024 * 1024)
    assert client.put("file5.txt", payload)
    assert client.get("file5.txt") == payload


def test_write_write_conflict_window(shim):
    sim, client = shim
    assert client.put("f.txt", b"v1")
    # second put inside the 60-round window without confirmation -> reject
    # ("Write-Write conflicts!", slave.go:681-686)
    assert not client.put("f.txt", b"v2")
    # with confirmation (the interactive yes) it goes through
    assert client.put("f.txt", b"v2", confirm=True)
    assert client.get("f.txt") == b"v2"


def test_get_put_info_and_update_file_version(shim):
    sim, client = shim
    info = client.call("GetPutInfo", file="a.txt")
    assert info["ok"] and info["version"] == 1
    assert len(info["replicas"]) == REPLICATION_FACTOR
    # conflicting second request without confirm
    info2 = client.call("GetPutInfo", file="a.txt")
    assert (info2["ok"], info2["conflict"]) == (False, True)
    # confirmed retry bumps the version
    info3 = client.call("GetPutInfo", file="a.txt", confirm=True)
    assert info3["ok"] and info3["version"] == 2
    # replica-side registry write + report (Update_file_version/Get_file_data)
    node = info["replicas"][0]
    client.call("UpdateFileVersion", node=node, file="a.txt", version=2)
    report = client.call("GetFileData", node=node, file="a.txt")
    assert report["local_version"] == 2


def test_remote_reput_copies_bytes(shim):
    sim, client = shim
    assert client.put("r.txt", b"replicate me")
    src = client.ls("r.txt")[0]
    target = next(i for i in range(12) if i not in client.ls("r.txt"))
    resp = client.call(
        "RemoteReput", source=src, target=target, file="r.txt", version=1
    )
    assert resp["ok"]
    assert client.store(target)["r.txt"] == 1


def test_vote_majority_elects(shim):
    sim, client = shim
    n_live = len(sim.cluster.live)
    candidate = 1
    for voter in range(n_live // 2 + 1):
        resp = client.call("Vote", candidate=candidate, voter=voter)
    assert resp["elected"]
    assert sim.cluster.master_node == candidate
    # all tallies (including losing candidates') clear once a master wins, so
    # stale voters can't count toward a later election
    resp = client.call("Vote", candidate=3, voter=0)
    server_votes = client.call("Vote", candidate=candidate, voter=0)["votes"]
    assert server_votes == 1


def test_assign_new_master_returns_listing(shim):
    sim, client = shim
    assert client.put("m.txt", b"x")
    node = client.ls("m.txt")[0]
    resp = client.call("AssignNewMaster", node=node, master=2)
    assert resp["listing"] == {"m.txt": 1}
    assert sim.cluster.master_node == 2


def test_get_update_meta_plans_repairs(shim):
    sim, client = shim
    assert client.put("p.txt", b"y")
    replicas = client.ls("p.txt")
    lost = replicas[0]
    view = [i for i in range(12) if i != lost]
    resp = client.call("GetUpdateMeta", membership=view)
    plans = resp["plans"]
    assert len(plans) == 1
    plan = plans[0]
    assert plan["file"] == "p.txt"
    assert lost not in plan["new_nodes"]
    assert set(plan["survivors"]) == set(replicas) - {lost}
    # planning only: cluster view/reachability/master are untouched
    assert sim.cluster.live == list(range(12))
    assert sim.cluster.reachable == set(range(12))
    assert sim.cluster.master_node == 0


def test_grep_searches_event_log(shim):
    sim, client = shim
    client.put("g.txt", b"z")
    lines = client.grep(r"put g\.txt")
    assert lines and lines[0]["kind"] == "put"


def test_delete_file_data_and_get_delete_info(shim):
    sim, client = shim
    assert client.put("d.txt", b"bytes")
    replicas = client.ls("d.txt")
    old = client.call("GetDeleteInfo", file="d.txt")["old_replicas"]
    assert set(old) == set(replicas)
    for node in old:
        assert client.call("DeleteFileData", node=node, file="d.txt")["ok"]
    assert client.store(old[0]) == {}


def test_method_surface_covers_reference_rpcs():
    """All 12 net/rpc methods (server/server.go) have a shim counterpart."""
    expected = {
        "Grep", "GetPutInfo", "GetFileData", "GetFileInfo",
        "AskForConfirmation", "GetDeleteInfo", "DeleteFileData",
        "RemoteReput", "Vote", "AssignNewMaster", "UpdateFileVersion",
        "GetUpdateMeta",
    }
    assert expected <= set(ShimServicer.METHODS)


def test_advance_bulk_serves_snapshot_reads(shim):
    """AdvanceBulk returns before the scan resolves; lsm/alive answer from
    the snapshot stream with an as_of_round tag, and the next synchronous
    verb rejoins exact reads (SURVEY §7.4's async boundary, end to end)."""
    import time

    sim, client = shim
    client.advance(3)  # counters past the hb grace
    client.crash(5)
    target = client.advance_bulk(20, snapshot_every=5)
    assert target == 23
    # snapshots flow in chunk by chunk while (or after) the scan runs; poll
    # until the final chunk (round 23) is served
    deadline = time.monotonic() + 120
    reply = {}
    while time.monotonic() < deadline:
        reply = client.call("Lsm", observer=0)
        if reply.get("as_of_round") == 23:
            break
        assert reply.get("as_of_round") in (None, 8, 13, 18, 23)
        time.sleep(0.005)
    assert reply.get("as_of_round") == 23
    assert 5 not in reply["members"]
    alive = client.call("AliveNodes")
    assert 5 not in alive["nodes"]
    # a synchronous advance resolves the bulk scan and drops the snapshot path
    client.advance(1)
    reply2 = client.call("Lsm", observer=0)
    assert "as_of_round" not in reply2
    assert 5 not in reply2["members"]


def test_conflict_confirmation_callback_roundtrip(shim):
    """VERDICT #4: a second client's put inside the 60-round window makes
    the master dial the FIRST requester's own shim server
    (AskForConfirmation, server.go:144-177); the requester's answer decides
    the put, and a dead/unresponsive requester times out to reject."""
    sim, client = shim
    # the requester runs its own server whose prompt says yes
    asked: list[str] = []

    def prompt(name: str) -> bool:
        asked.append(name)
        return True

    requester = ShimServer(
        CoSim(SimConfig(n=4), seed=9), port=0, confirm_handler=prompt
    ).start()
    try:
        assert client.call("Put", file="w.txt", data_b64="", )["ok"] is True
        # conflicting put WITH a callback: master -> requester round-trip
        reply = client.call(
            "GetPutInfo", file="w.txt", callback=requester.address
        )
        assert reply["ok"] is True
        assert asked == ["w.txt"]
        # conflicting put with a requester whose prompt says no
        requester.servicer.confirm_handler = lambda name: False
        reply = client.call(
            "GetPutInfo", file="w.txt", callback=requester.address
        )
        assert (reply["ok"], reply["conflict"]) == (False, True)
    finally:
        requester.stop()
    # no callback, no confirm, no auto-confirm: straight reject
    assert client.call("GetPutInfo", file="w.txt")["conflict"] is True


def test_conflict_confirmation_timeout_rejects():
    """The no-answer outcome (server.go:172): a requester that ACCEPTS the
    connection but never answers is a reject after confirm_timeout seconds
    — the reference's 30 s ceiling, shortened here so CI doesn't stall."""
    import socket
    import time

    sim = CoSim(SimConfig(n=12), seed=3)
    server = ShimServer(sim, port=0, confirm_timeout=1.0).start()
    client = ShimClient(server.address, timeout=30.0)
    # a listening socket that never speaks gRPC: connects succeed, the
    # AskForConfirmation call hangs until the master's deadline fires
    silent = socket.socket()
    silent.bind(("127.0.0.1", 0))
    silent.listen(1)
    blackhole = f"127.0.0.1:{silent.getsockname()[1]}"
    try:
        assert client.call("Put", file="t.txt", data_b64="")["ok"] is True
        t0 = time.monotonic()
        reply = client.call("GetPutInfo", file="t.txt", callback=blackhole)
        elapsed = time.monotonic() - t0
        assert (reply["ok"], reply["conflict"]) == (False, True)
        assert 0.9 <= elapsed < 10.0  # the deadline, not a hang
        # connection-refused rejects too (fast-fail flavour of no answer)
        reply = client.call("GetPutInfo", file="t.txt", callback="127.0.0.1:9")
        assert (reply["ok"], reply["conflict"]) == (False, True)
    finally:
        silent.close()
        client.close()
        server.stop()


def test_put_verb_forwards_callback(shim):
    """The whole-op Put verb drives the same callback round-trip."""
    sim, client = shim
    answers = iter([True, False])
    requester = ShimServer(
        CoSim(SimConfig(n=4), seed=9), port=0,
        confirm_handler=lambda name: next(answers),
    ).start()
    try:
        assert client.put("v.txt", b"abc") is True
        ok = client.call(
            "Put", file="v.txt", data_b64="", callback=requester.address
        )["ok"]
        assert ok is True   # first answer: yes
        ok = client.call(
            "Put", file="v.txt", data_b64="", callback=requester.address
        )["ok"]
        assert ok is False  # second answer: no
    finally:
        requester.stop()
