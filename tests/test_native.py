"""Native (C++) gossip runtime: codec parity + live epoll-engine behavior.

The C++ engine (native/engine.cc) must speak exactly the wire format and
protocol semantics of the Python asyncio parity path (detector/udp.py), both
mirroring the reference (slave/slave.go).  Timing-dependent tests use generous
periods for the 1-core box.
"""

from __future__ import annotations

import pathlib
import shutil
import sys
import time

import pytest

if shutil.which("g++") is None or shutil.which("make") is None:
    pytest.skip("no native toolchain", allow_module_level=True)

from gossipfs_tpu import native

# Round 15: force the staleness check BEFORE anything loads the library.
# The old flow only rebuilt on strictly-newer source mtimes, so a fresh
# checkout (every file stamped alike) or a stray committed .so ran the
# whole module silently against a binary built from DIFFERENT sources.
# ensure_fresh() rebuilds on at-or-newer sources (Makefile included),
# and a broken rebuild is a loud collection failure — never a skip that
# hides a compile error in engine.cc.
try:
    native.ensure_fresh()
except native.NativeBuildError as e:
    pytest.fail(f"native sources changed but the rebuild failed:\n{e}",
                pytrace=False)

from gossipfs_tpu.detector.udp import ENTRY_SEP, FIELD_SEP, UdpNode


class TestCodecParity:
    def test_encode_matches_python_framing(self):
        entries = [
            ("127.0.0.1:8000", 17, 3.5),
            ("127.0.0.1:8001", 0, 0.0),
        ]
        wire = native.codec_encode(entries)
        assert ENTRY_SEP in wire and FIELD_SEP in wire
        # the Python decoder reads the C++ encoder's output
        decoded = UdpNode._decode(wire)
        assert decoded == [
            ("127.0.0.1:8000", 17, 3.5),
            ("127.0.0.1:8001", 0, 0.0),
        ]

    def test_cpp_decodes_python_style_wire(self):
        wire = ENTRY_SEP.join(
            f"addr{i}{FIELD_SEP}{i * 3}{FIELD_SEP}{i}.25" for i in range(5)
        )
        decoded = native.codec_decode(wire)
        assert [(a, hb) for a, hb, _ in decoded] == [
            (f"addr{i}", i * 3) for i in range(5)
        ]

    def test_roundtrip(self):
        entries = [(f"10.0.0.{i}:8000", i * 7, float(i)) for i in range(1, 9)]
        assert native.codec_decode(native.codec_encode(entries)) == entries

    def test_roundtrip_preserves_large_timestamps(self):
        # monotonic clocks on long-uptime hosts exceed 1e5 s; sub-second
        # resolution must survive the wire (full round-trip precision)
        entries = [("10.0.0.1:8000", 42, 1785344960.123456)]
        assert native.codec_decode(native.codec_encode(entries)) == entries

    def test_malformed_chunks_skipped(self):
        wire = f"good{FIELD_SEP}5{FIELD_SEP}1.0{ENTRY_SEP}bad-no-fields{ENTRY_SEP}x{FIELD_SEP}NaNish"
        decoded = native.codec_decode(wire)
        assert decoded[0][:2] == ("good", 5)
        assert all(a != "bad-no-fields" for a, _, _ in decoded)
        # "NaNish" parses as NaN under strtod: entry must be skipped, not
        # cast (undefined behavior) into a garbage heartbeat
        assert all(a != "x" for a, _, _ in decoded)


class TestNativeEngine:
    def test_converges_detects_and_rejoins(self):
        with native.NativeUdpDetector(
            n=8, base_port=19500, period=0.1, fresh_cooldown=True
        ) as det:
            det.advance(4)
            # full convergence: everyone sees everyone
            for obs in range(8):
                assert det.membership(obs) == list(range(8))
            assert det.alive_nodes() == list(range(8))

            det.crash(5)
            det.advance(12)  # t_fail=5 periods + dissemination slack
            assert 5 not in det.alive_nodes()
            events = det.drain_events()
            assert any(
                e.subject == 5 and not e.false_positive for e in events
            ), events
            for obs in (0, 3, 7):
                assert 5 not in det.membership(obs)

            # rejoin through the introducer; cooldown must expire first
            det.advance(8)
            det.join(5)
            det.advance(10)
            assert 5 in det.alive_nodes()
            assert 5 in det.membership(0)

    def test_three_engine_detection_parity(self):
        """Native C++, Python asyncio-UDP, and the tensor sim all detect a
        crash in the same round band: crash at round r with warm heartbeats
        -> first detection within [r + t_fail - 1, r + t_fail + slack]
        (slack covers real-socket scheduling jitter; the sim is exact —
        tests/test_golden_parity.py pins it per-round)."""
        import asyncio

        import jax.numpy as jnp

        from gossipfs_tpu.config import SimConfig
        from gossipfs_tpu.core.rounds import run_rounds
        from gossipfs_tpu.core.state import RoundEvents, init_state
        from gossipfs_tpu.detector.udp import UdpCluster

        t_fail, n, crash_at, slack = 5, 10, 8, 4
        bands = {}

        # native C++ engine
        with native.NativeUdpDetector(
            n=n, base_port=19700, period=0.1, fresh_cooldown=True
        ) as det:
            det.advance(crash_at)
            r0 = det.round
            det.crash(4)
            det.advance(t_fail + slack + 2)
            events = [e for e in det.drain_events() if e.subject == 4]
            assert events, "native engine never detected the crash"
            bands["native"] = min(e.round for e in events) - r0

        # python asyncio engine
        async def py_scenario():
            c = UdpCluster(n=n, base_port=19800, period=0.1, fresh_cooldown=True)
            try:
                await c.start_all()
                await c.run(crash_at)
                r0 = c._round
                c.crash(4)
                await c.run(t_fail + slack + 2)
                return [e for e in c.drain_events() if e.subject == 4], r0
            finally:
                c.stop_all()

        events, r0 = asyncio.run(py_scenario())
        assert events, "python engine never detected the crash"
        bands["python"] = min(e.round for e in events) - r0

        # tensor sim (ring parity config, same constants)
        cfg = SimConfig(n=n, t_fail=t_fail, fresh_cooldown=True)
        rounds = crash_at + t_fail + slack + 2
        crash = jnp.zeros((rounds, n), dtype=bool).at[crash_at, 4].set(True)
        zeros = jnp.zeros((rounds, n), dtype=bool)
        events_sched = RoundEvents(crash=crash, leave=zeros, join=zeros)
        import jax

        _, carry, _ = run_rounds(
            init_state(cfg), cfg, rounds, jax.random.PRNGKey(0),
            events=events_sched,
        )
        bands["sim"] = int(carry.first_detect[4]) - crash_at

        for engine, rel in bands.items():
            assert t_fail - 1 <= rel <= t_fail + slack, (engine, bands)

    def test_graceful_leave_disseminates(self):
        with native.NativeUdpDetector(
            n=6, base_port=19600, period=0.1, fresh_cooldown=True
        ) as det:
            det.advance(4)
            det.leave(2)
            det.advance(3)  # LEAVE broadcast: removal is immediate, no t_fail
            assert 2 not in det.alive_nodes()
            assert 2 not in det.membership(0)
            # a voluntary leave is not a failure detection
            assert all(e.subject != 2 for e in det.drain_events())


class TestNativeObs:
    """Round 16: the epoll engine as an obs-plane producer — events
    drained over ``gfs_obs_drain`` and rendered through the ONE schema,
    vitals under the n/a-not-0 rule, fault gates at the send seam, and
    the SWIM lifecycle running inside the engine."""

    def _run_crash(self, base_port, n=10, victims=(4, 7), rounds=12,
                   path=None, recorder=None):
        """One seeded crash run; returns (recorder, drain_events, r0)."""
        from gossipfs_tpu.obs.recorder import FlightRecorder

        det = native.NativeUdpDetector(
            n=n, base_port=base_port, period=0.05, fresh_cooldown=True)
        try:
            det.seed_full_membership()
            deadline = time.monotonic() + 30
            while not det.warm():
                assert time.monotonic() < deadline, "warmup stalled"
                time.sleep(0.05)
            rec = recorder if recorder is not None else FlightRecorder(
                path, source="native", n=n,
                crash_rounds={str(v): 0 for v in victims})
            r0 = det.attach_recorder(rec)
            for v in victims:
                det.crash(v)
            det.advance(rounds)
            det.stop()
            det.pump_obs()
            events = det.drain_events()
            rec.close()
            return rec, events, r0
        finally:
            det.close()

    def test_monitor_matches_drain_events(self):
        """THE standing oracle, extended to the third engine: the
        StreamMonitor's estimators derived from the recorded native
        stream must equal the ``drain_events``-derived ground truth
        EXACTLY — detections, false positives, per-victim first-detect
        TTD — on a seeded crash run."""
        from gossipfs_tpu.obs.monitor import StreamMonitor

        victims = (4, 7)
        rec, devents, r0 = self._run_crash(22100, victims=victims)
        mon = StreamMonitor(n=10)
        mon.observe_header(rec.header)
        mon.feed(rec.events)
        mon.finish()
        s = mon.summary()

        # ground truth from the int-buffer drain (absolute rounds ->
        # the stream's rebased frame via r0)
        fp_truth = sum(1 for e in devents if e.false_positive)
        assert s["false_positives"] == fp_truth
        first = {}
        for e in devents:
            if e.subject in victims:
                first.setdefault(e.subject, e.round - r0)
                first[e.subject] = min(first[e.subject], e.round - r0)
        assert s["detected"] == len(first) == len(victims)
        for v in victims:
            # header crash_rounds stamp the crash at stream round 0
            assert s["ttd_first"][v] == first[v]
        # the round_tick deltas and the drain buffer count the SAME
        # RecordDetection increments
        assert s["true_detections"] + s["false_positives"] == len(devents)

    def test_native_tensor_lifecycle_parity(self):
        """Three-engine trace parity, native vs tensor: the same seeded
        crash produces the same per-subject lifecycle kind-sequence
        [crash, hb_freeze, confirm, remove] through tools/timeline.py's
        canonical ordering."""
        import jax
        import jax.numpy as jnp

        from gossipfs_tpu.config import SimConfig
        from gossipfs_tpu.core.rounds import run_rounds
        from gossipfs_tpu.core.state import RoundEvents, init_state
        from gossipfs_tpu.obs.recorder import decode_scan

        sys.path.insert(0, str(
            pathlib.Path(__file__).resolve().parents[1] / "tools"))
        import timeline as tl

        rec, _, _ = self._run_crash(22200, victims=(4,), rounds=14)
        native_seq = tl.kind_sequence(rec.events, 4)

        # crash past the hb<=1 grace (a round-0 victim is permanently
        # grace-protected in the tensor engine; the native run seeds +
        # warms past the grace before crashing, so both are warm kills)
        n, rounds, crash_at = 10, 16, 4
        cfg = SimConfig(n=n, t_fail=5, fresh_cooldown=True)
        crash = jnp.zeros((rounds, n), dtype=bool).at[crash_at, 4].set(True)
        zeros = jnp.zeros((rounds, n), dtype=bool)
        _, carry, per_round = run_rounds(
            init_state(cfg), cfg, rounds, jax.random.PRNGKey(0),
            events=RoundEvents(crash=crash, leave=zeros, join=zeros))
        tensor_events = decode_scan(per_round, carry, n=n,
                                    crash_rounds={4: crash_at})
        tensor_seq = tl.kind_sequence(tensor_events, 4)
        assert native_seq == tensor_seq == [
            "crash", "hb_freeze", "confirm", "remove"]

    def test_timeline_ingests_native_stream_unchanged(self, tmp_path):
        """A native trace is a plain gossipfs-obs/v1 stream: timeline's
        analyze re-derives the run's metrics from the file alone."""
        sys.path.insert(0, str(
            pathlib.Path(__file__).resolve().parents[1] / "tools"))
        import timeline as tl

        path = tmp_path / "native.jsonl"
        rec, devents, _ = self._run_crash(22300, path=str(path))
        header, events = tl.load_stream(str(path))
        assert header["schema"] == "gossipfs-obs/v1"
        assert header["source"] == "native"
        doc = tl.analyze([header], events)
        assert doc["rounds"] > 0
        assert doc["detected"] == 2
        assert doc["false_positives"] == sum(
            1 for e in devents if e.false_positive)
        assert set(doc["ttd_first"]) == {4, 7}

    def test_feed_jsonl_refeed_never_double_counts(self, tmp_path):
        """A MonitorRecorder-written native stream re-fed through a
        fresh StreamMonitor re-derives, never double-counts: estimator
        parity field-for-field, violations re-derived not appended."""
        from gossipfs_tpu.obs.monitor import (
            MonitorRecorder,
            StreamMonitor,
            estimator_parity,
        )

        path = tmp_path / "monitored.jsonl"
        inline = MonitorRecorder(str(path), source="native", n=10,
                                 crash_rounds={"4": 0, "7": 0})
        self._run_crash(22400, recorder=inline)
        fresh = StreamMonitor(n=10)
        fresh.feed_jsonl(str(path))
        fresh.finish()
        parity = estimator_parity(inline.monitor.summary(),
                                  fresh.summary())
        assert parity["ok"], parity["mismatches"]
        assert len(fresh.violations) == len(inline.monitor.violations)

    def test_vitals_na_not_zero(self):
        """The uniform-vitals surface: fields the engine cannot know (or
        hasn't armed) are ABSENT and render n/a — never a fabricated 0;
        arming suspicion makes its counters appear."""
        from gossipfs_tpu.obs.schema import render_vitals
        from gossipfs_tpu.suspicion import SuspicionParams

        with native.NativeUdpDetector(n=6, base_port=22500,
                                      period=0.05) as det:
            det.advance(2)
            doc = det.vitals()
            assert doc["engine"] == "native"
            assert doc["round"] >= 1 and doc["n_alive"] == 6
            assert "suspects_now" not in doc  # suspicion off -> absent
            assert "fp_suppressed" not in doc  # sim-only ground truth
            rendered = render_vitals(doc)
            assert "fp_suppressed=n/a" in rendered
            assert "suspects_now=n/a" in rendered
            assert "ops_issued=n/a" in rendered
        with native.NativeUdpDetector(
                n=6, base_port=22600, period=0.05,
                suspicion=SuspicionParams(t_suspect=2)) as det:
            det.advance(2)
            doc = det.vitals()
            for field in ("suspects_now", "suspects_entered",
                          "refutations", "confirms"):
                assert field in doc, field

    def test_scenario_gate_and_suspicion_refute(self):
        """The fault-gate table at the send seam + the in-engine SWIM
        lifecycle: a flapped (alive!) node is confirmed as a false
        positive by the raw detector, and with a wide-enough suspect
        window the same flap is SUSPECTED then REFUTED — no confirm."""
        from gossipfs_tpu.obs.recorder import FlightRecorder
        from gossipfs_tpu.scenarios.schedule import FaultScenario, Flapping
        from gossipfs_tpu.suspicion import SuspicionParams

        def run(base_port, suspicion, down, rounds):
            sc = FaultScenario(
                name="flap-gate", n=8,
                flapping=(Flapping(start=2, end=2 + down + 4, up=1,
                                   down=down, nodes=(6,)),))
            det = native.NativeUdpDetector(
                n=8, base_port=base_port, period=0.05,
                fresh_cooldown=True, suspicion=suspicion)
            try:
                det.seed_full_membership()
                deadline = time.monotonic() + 30
                while not det.warm():
                    assert time.monotonic() < deadline
                    time.sleep(0.05)
                rec = FlightRecorder(None, source="native", n=8)
                r0 = det.attach_recorder(rec)
                det.load_scenario(sc, round0=r0)
                det.advance(rounds)
                det.stop()
                det.pump_obs()
                return rec, det.vitals()
            finally:
                det.close()

        # raw: the dark span outlives t_fail -> false-positive confirm
        rec, _ = run(22700, None, down=10, rounds=18)
        fp6 = [e for e in rec.events
               if e.kind == "confirm" and e.subject == 6]
        assert fp6 and all(e.detail["false_positive"] for e in fp6)
        # armed: suspect -> refute on recovery, never confirmed
        rec, vit = run(22800, SuspicionParams(t_suspect=20), down=8,
                       rounds=22)
        kinds6 = [e.kind for e in rec.events if e.subject == 6]
        assert "suspect" in kinds6 and "refute" in kinds6
        assert "confirm" not in kinds6
        assert vit["suspects_entered"] > 0 and vit["refutations"] > 0

    def test_latency_histogram(self):
        """Every round_tick carries the tick pass's wall-clock cost; the
        histogram helper rolls them up (absent quantiles on an empty
        stream — the n/a rule)."""
        rec, _, _ = self._run_crash(22900, rounds=8)
        hist = native.latency_histogram(rec.events)
        assert hist["count"] >= 8
        assert hist["p50_ms"] > 0
        assert sum(hist["buckets"].values()) == hist["count"]
        assert native.latency_histogram([]) == {"count": 0}


def test_native_rt_bench_smoke():
    """The native-runtime benchmark runs the real-socket protocol faster
    than the reference's 1 round/s wall clock and still detects in ~t_fail."""
    from gossipfs_tpu.bench.native_rt import run

    out = run(n=10, period=0.02, rounds=30)
    assert out["rounds_per_sec"] > 10       # >> the reference's 1 round/s
    assert 4 <= out["detection_rounds"] <= 8
