"""Native (C++) gossip runtime: codec parity + live epoll-engine behavior.

The C++ engine (native/engine.cc) must speak exactly the wire format and
protocol semantics of the Python asyncio parity path (detector/udp.py), both
mirroring the reference (slave/slave.go).  Timing-dependent tests use generous
periods for the 1-core box.
"""

from __future__ import annotations

import shutil

import pytest

if shutil.which("g++") is None or shutil.which("make") is None:
    pytest.skip("no native toolchain", allow_module_level=True)

from gossipfs_tpu import native

# Round 15: force the staleness check BEFORE anything loads the library.
# The old flow only rebuilt on strictly-newer source mtimes, so a fresh
# checkout (every file stamped alike) or a stray committed .so ran the
# whole module silently against a binary built from DIFFERENT sources.
# ensure_fresh() rebuilds on at-or-newer sources (Makefile included),
# and a broken rebuild is a loud collection failure — never a skip that
# hides a compile error in engine.cc.
try:
    native.ensure_fresh()
except native.NativeBuildError as e:
    pytest.fail(f"native sources changed but the rebuild failed:\n{e}",
                pytrace=False)

from gossipfs_tpu.detector.udp import ENTRY_SEP, FIELD_SEP, UdpNode


class TestCodecParity:
    def test_encode_matches_python_framing(self):
        entries = [
            ("127.0.0.1:8000", 17, 3.5),
            ("127.0.0.1:8001", 0, 0.0),
        ]
        wire = native.codec_encode(entries)
        assert ENTRY_SEP in wire and FIELD_SEP in wire
        # the Python decoder reads the C++ encoder's output
        decoded = UdpNode._decode(wire)
        assert decoded == [("127.0.0.1:8000", 17), ("127.0.0.1:8001", 0)]

    def test_cpp_decodes_python_style_wire(self):
        wire = ENTRY_SEP.join(
            f"addr{i}{FIELD_SEP}{i * 3}{FIELD_SEP}{i}.25" for i in range(5)
        )
        decoded = native.codec_decode(wire)
        assert [(a, hb) for a, hb, _ in decoded] == [
            (f"addr{i}", i * 3) for i in range(5)
        ]

    def test_roundtrip(self):
        entries = [(f"10.0.0.{i}:8000", i * 7, float(i)) for i in range(1, 9)]
        assert native.codec_decode(native.codec_encode(entries)) == entries

    def test_roundtrip_preserves_large_timestamps(self):
        # monotonic clocks on long-uptime hosts exceed 1e5 s; sub-second
        # resolution must survive the wire (full round-trip precision)
        entries = [("10.0.0.1:8000", 42, 1785344960.123456)]
        assert native.codec_decode(native.codec_encode(entries)) == entries

    def test_malformed_chunks_skipped(self):
        wire = f"good{FIELD_SEP}5{FIELD_SEP}1.0{ENTRY_SEP}bad-no-fields{ENTRY_SEP}x{FIELD_SEP}NaNish"
        decoded = native.codec_decode(wire)
        assert decoded[0][:2] == ("good", 5)
        assert all(a != "bad-no-fields" for a, _, _ in decoded)
        # "NaNish" parses as NaN under strtod: entry must be skipped, not
        # cast (undefined behavior) into a garbage heartbeat
        assert all(a != "x" for a, _, _ in decoded)


class TestNativeEngine:
    def test_converges_detects_and_rejoins(self):
        with native.NativeUdpDetector(
            n=8, base_port=19500, period=0.1, fresh_cooldown=True
        ) as det:
            det.advance(4)
            # full convergence: everyone sees everyone
            for obs in range(8):
                assert det.membership(obs) == list(range(8))
            assert det.alive_nodes() == list(range(8))

            det.crash(5)
            det.advance(12)  # t_fail=5 periods + dissemination slack
            assert 5 not in det.alive_nodes()
            events = det.drain_events()
            assert any(
                e.subject == 5 and not e.false_positive for e in events
            ), events
            for obs in (0, 3, 7):
                assert 5 not in det.membership(obs)

            # rejoin through the introducer; cooldown must expire first
            det.advance(8)
            det.join(5)
            det.advance(10)
            assert 5 in det.alive_nodes()
            assert 5 in det.membership(0)

    def test_three_engine_detection_parity(self):
        """Native C++, Python asyncio-UDP, and the tensor sim all detect a
        crash in the same round band: crash at round r with warm heartbeats
        -> first detection within [r + t_fail - 1, r + t_fail + slack]
        (slack covers real-socket scheduling jitter; the sim is exact —
        tests/test_golden_parity.py pins it per-round)."""
        import asyncio

        import jax.numpy as jnp

        from gossipfs_tpu.config import SimConfig
        from gossipfs_tpu.core.rounds import run_rounds
        from gossipfs_tpu.core.state import RoundEvents, init_state
        from gossipfs_tpu.detector.udp import UdpCluster

        t_fail, n, crash_at, slack = 5, 10, 8, 4
        bands = {}

        # native C++ engine
        with native.NativeUdpDetector(
            n=n, base_port=19700, period=0.1, fresh_cooldown=True
        ) as det:
            det.advance(crash_at)
            r0 = det.round
            det.crash(4)
            det.advance(t_fail + slack + 2)
            events = [e for e in det.drain_events() if e.subject == 4]
            assert events, "native engine never detected the crash"
            bands["native"] = min(e.round for e in events) - r0

        # python asyncio engine
        async def py_scenario():
            c = UdpCluster(n=n, base_port=19800, period=0.1, fresh_cooldown=True)
            try:
                await c.start_all()
                await c.run(crash_at)
                r0 = c._round
                c.crash(4)
                await c.run(t_fail + slack + 2)
                return [e for e in c.drain_events() if e.subject == 4], r0
            finally:
                c.stop_all()

        events, r0 = asyncio.run(py_scenario())
        assert events, "python engine never detected the crash"
        bands["python"] = min(e.round for e in events) - r0

        # tensor sim (ring parity config, same constants)
        cfg = SimConfig(n=n, t_fail=t_fail, fresh_cooldown=True)
        rounds = crash_at + t_fail + slack + 2
        crash = jnp.zeros((rounds, n), dtype=bool).at[crash_at, 4].set(True)
        zeros = jnp.zeros((rounds, n), dtype=bool)
        events_sched = RoundEvents(crash=crash, leave=zeros, join=zeros)
        import jax

        _, carry, _ = run_rounds(
            init_state(cfg), cfg, rounds, jax.random.PRNGKey(0),
            events=events_sched,
        )
        bands["sim"] = int(carry.first_detect[4]) - crash_at

        for engine, rel in bands.items():
            assert t_fail - 1 <= rel <= t_fail + slack, (engine, bands)

    def test_graceful_leave_disseminates(self):
        with native.NativeUdpDetector(
            n=6, base_port=19600, period=0.1, fresh_cooldown=True
        ) as det:
            det.advance(4)
            det.leave(2)
            det.advance(3)  # LEAVE broadcast: removal is immediate, no t_fail
            assert 2 not in det.alive_nodes()
            assert 2 not in det.membership(0)
            # a voluntary leave is not a failure detection
            assert all(e.subject != 2 for e in det.drain_events())


def test_native_rt_bench_smoke():
    """The native-runtime benchmark runs the real-socket protocol faster
    than the reference's 1 round/s wall clock and still detects in ~t_fail."""
    from gossipfs_tpu.bench.native_rt import run

    out = run(n=10, period=0.02, rounds=30)
    assert out["rounds_per_sec"] > 10       # >> the reference's 1 round/s
    assert 4 <= out["detection_rounds"] <= 8
