"""Pallas merge kernel: interpret-mode equivalence against the XLA oracle.

The kernel (ops/merge_pallas.py) must be bit-identical to the XLA gather
formulation — the golden-parity suite pins the XLA path to the reference
protocol, so kernel == oracle implies kernel == reference.  These tests run
the kernel in interpreter mode on CPU; the real-TPU timing lives in bench.py.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import pytest

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.core.rounds import run_rounds
from gossipfs_tpu.core.state import init_state
from gossipfs_tpu.ops.merge_pallas import (
    fanout_max_merge,
    fanout_max_merge_xla,
    supported,
)


@pytest.mark.parametrize("dtype", [jnp.int32, jnp.int16, jnp.int8])
@pytest.mark.parametrize("n,fanout", [
    (128, 3), (256, 8),
    pytest.param(384, 17, marks=pytest.mark.slow),  # biggest interpret run
])
def test_kernel_matches_oracle(n, fanout, dtype):
    key = jax.random.PRNGKey(n + fanout)
    k1, k2 = jax.random.split(key)
    # int16/int8 are the production view dtypes (core/rounds.py rebases
    # heartbeats into config.view_dtype); int32 keeps the kernel dtype-generic
    view = jax.random.randint(k1, (n, n), -1, 100, dtype=jnp.int32).astype(dtype)
    edges = jax.random.randint(k2, (n, fanout), 0, n, dtype=jnp.int32)
    got = fanout_max_merge(view, edges, interpret=True)
    want = fanout_max_merge_xla(view, edges)
    assert got.dtype == dtype
    assert jnp.array_equal(got, want)


def test_kernel_blocks_smaller_than_defaults():
    # N smaller than the default block sizes: blocks must shrink to fit
    n, fanout = 128, 4
    view = jax.random.randint(jax.random.PRNGKey(0), (n, n), -1, 50, jnp.int32)
    edges = jax.random.randint(jax.random.PRNGKey(1), (n, fanout), 0, n, jnp.int32)
    got = fanout_max_merge(
        view, edges, block_r=512, block_c=8192, slots=8, interpret=True
    )
    assert jnp.array_equal(got, fanout_max_merge_xla(view, edges))


def test_unsupported_shapes_rejected():
    assert not supported(100, 3)  # not lane-aligned
    assert supported(256, 3)
    view = jnp.zeros((100, 100), dtype=jnp.int32)
    edges = jnp.zeros((100, 3), dtype=jnp.int32)
    with pytest.raises(ValueError, match="XLA path"):
        fanout_max_merge(view, edges, interpret=True)


def test_full_round_equivalence_xla_vs_pallas():
    """run_rounds with merge_kernel=pallas_interpret reproduces the XLA
    scan bit-for-bit (states, detection rounds, per-round metrics)."""
    base = SimConfig(
        n=128,
        topology="random",
        fanout=5,
        remove_broadcast=False,
        fresh_cooldown=True,
    )
    key = jax.random.PRNGKey(7)
    out = {}
    for kernel in ("xla", "pallas_interpret"):
        cfg = dataclasses.replace(base, merge_kernel=kernel)
        state = init_state(cfg)
        final, carry, per_round = run_rounds(
            state, cfg, 12, key, crash_rate=0.02, rejoin_rate=0.01
        )
        out[kernel] = (final, carry, per_round)

    fx, cx, px = out["xla"]
    fp, cp, pp = out["pallas_interpret"]
    assert jnp.array_equal(fx.hb, fp.hb)
    assert jnp.array_equal(fx.age, fp.age)
    assert jnp.array_equal(fx.status, fp.status)
    assert jnp.array_equal(fx.alive, fp.alive)
    assert jnp.array_equal(cx.first_detect, cp.first_detect)
    assert jnp.array_equal(cx.converged, cp.converged)
    assert jnp.array_equal(px.true_detections, pp.true_detections)
    assert jnp.array_equal(px.false_positives, pp.false_positives)


@pytest.mark.slow  # N=4096 interpreter-mode kernel run
def test_stripe_kernel_matches_oracle():
    """The VMEM-stripe kernel == XLA formulation, through the full epilogue.

    Exercised via the public entry (stripe_merge_update_blocked) against
    fused_merge_update_blocked, which the other tests pin to the XLA path.
    """
    from gossipfs_tpu.config import AGE_CLAMP
    from gossipfs_tpu.core.state import MEMBER, UNKNOWN
    from gossipfs_tpu.ops.merge_pallas import (
        STRIPE_BLOCK_C,
        blocked_shape,
        fused_merge_update_blocked,
        stripe_merge_update_blocked,
    )

    n, fanout = 4096, 6
    key = jax.random.PRNGKey(11)
    ks = jax.random.split(key, 7)
    shp = blocked_shape(n, STRIPE_BLOCK_C)
    view = jax.random.randint(ks[0], (n, n), -1, 100, jnp.int32).astype(jnp.int8)
    edges = jax.random.randint(ks[1], (n, fanout), 0, n, jnp.int32)
    hb = jax.random.randint(ks[2], (n, n), 0, 120, jnp.int32).astype(jnp.int16)
    age = jax.random.randint(ks[3], (n, n), 0, 30, jnp.int32).astype(jnp.int8)
    status = jax.random.randint(ks[4], (n, n), 0, 3, jnp.int32).astype(jnp.int8)
    shift_a = jax.random.randint(ks[5], (n,), 0, 5, jnp.int32)
    shift_b = jnp.zeros((n,), jnp.int32)
    alive = (jax.random.uniform(ks[6], (n,)) > 0.1).astype(jnp.int32)
    # protocol invariant the kernels' two dead-receiver mechanisms (edge
    # remap to self vs explicit liveness gate) both rely on: a dead node
    # never sends, so its view row is all -1
    view = jnp.where((alive != 0)[:, None], view, jnp.int8(-1))
    args = (
        view.reshape(shp), edges, hb.reshape(shp), age.reshape(shp),
        status.reshape(shp), shift_a.reshape(shp[1:]),
        shift_b.reshape(shp[1:]), alive,
    )
    kw = dict(member=int(MEMBER), unknown=int(UNKNOWN), age_clamp=AGE_CLAMP,
              interpret=True)
    want = fused_merge_update_blocked(*args, **kw)
    *got, cnt, _ndet, _fobs = stripe_merge_update_blocked(*args, **kw)
    for g, w, name in zip(got, want, ("hb", "age", "status")):
        assert jnp.array_equal(g, w), name
    # the member-count side output == the live-row column count (incl. self)
    st_new = got[2].reshape(n, n)
    want_cnt = jnp.sum(
        ((alive != 0)[:, None]) & (st_new == MEMBER), axis=0, dtype=jnp.int32
    )
    assert jnp.array_equal(cnt.reshape(n), want_cnt)


def test_arc_edges_expand_to_consecutive_window():
    import numpy as np

    from gossipfs_tpu.core.topology import arc_edges, random_arc_bases

    n, fanout = 256, 7
    bases = random_arc_bases(jax.random.PRNGKey(5), n, fanout)
    edges = np.asarray(arc_edges(bases, fanout))
    b = np.asarray(bases)
    for i in (0, 17, 255):
        assert list(edges[i]) == [(b[i] + k) % n for k in range(fanout)]
        # never-self: the arc excludes the receiver
        assert i not in edges[i]
    # bases uniform over the n-fanout non-covering starts: all observed
    # windows must exclude self for every receiver
    assert all(i not in edges[i] for i in range(n))


@pytest.mark.slow  # N=4096 interpreter-mode kernel run
def test_full_round_equivalence_xla_vs_arc_stripe():
    """random_arc: the windowed-stripe kernel == the XLA gather over the
    expanded [N, F] arc edges, bit-for-bit through full rounds."""
    base = SimConfig(
        n=4096,
        topology="random_arc",
        fanout=6,
        remove_broadcast=False,
        fresh_cooldown=True,
        view_dtype="int8",
        merge_block_c=4096,
    )
    key = jax.random.PRNGKey(9)
    out = {}
    for kernel in ("xla", "pallas_stripe_interpret"):
        cfg = dataclasses.replace(base, merge_kernel=kernel)
        final, carry, per_round = run_rounds(
            init_state(cfg), cfg, 6, key, crash_rate=0.01
        )
        out[kernel] = (final, carry, per_round)
    fx, cx, px = out["xla"]
    fp, cp, pp = out["pallas_stripe_interpret"]
    assert jnp.array_equal(fx.hb, fp.hb)
    assert jnp.array_equal(fx.age, fp.age)
    assert jnp.array_equal(fx.status, fp.status)
    assert jnp.array_equal(cx.first_detect, cp.first_detect)
    assert jnp.array_equal(cx.first_observer, cp.first_observer)
    assert jnp.array_equal(px.true_detections, pp.true_detections)
    assert jnp.array_equal(px.false_positives, pp.false_positives)


@pytest.mark.slow  # N=4096 interpreter-mode kernel run
def test_full_round_equivalence_xla_vs_stripe():
    """run_rounds with merge_kernel=pallas_stripe_interpret reproduces the
    XLA scan bit-for-bit at a stripe-eligible size."""
    base = SimConfig(
        n=4096,
        topology="random",
        fanout=6,
        remove_broadcast=False,
        fresh_cooldown=True,
        view_dtype="int8",
        merge_block_c=4096,
    )
    key = jax.random.PRNGKey(3)
    out = {}
    for kernel in ("xla", "pallas_stripe_interpret"):
        cfg = dataclasses.replace(base, merge_kernel=kernel)
        final, carry, per_round = run_rounds(
            init_state(cfg), cfg, 6, key, crash_rate=0.01
        )
        out[kernel] = (final, carry, per_round)
    fx, cx, px = out["xla"]
    fp, cp, pp = out["pallas_stripe_interpret"]
    assert jnp.array_equal(fx.hb, fp.hb)
    assert jnp.array_equal(fx.age, fp.age)
    assert jnp.array_equal(fx.status, fp.status)
    assert jnp.array_equal(cx.first_detect, cp.first_detect)
    assert jnp.array_equal(cx.first_observer, cp.first_observer)
    assert jnp.array_equal(px.true_detections, pp.true_detections)


@pytest.mark.slow  # N=4096 interpreter-mode kernel run
@pytest.mark.parametrize("block_c,rr_resident,topology,arc_align,elementwise", [
    (4096, "off", "random", 1, "lanes"),
    (1024, "off", "random", 1, "lanes"),
    (1024, "on", "random", 1, "lanes"),
    (2048, "on", "random_arc", 1, "lanes"),
    # the round-5 headline shape (bench.py): tile-aligned arcs — bases are
    # multiples of 8, the kernel's window-max is a group reduction riding
    # the view build + one pair-max, and the XLA oracle expands the same
    # aligned bases, so the two paths must stay bit-identical
    (2048, "on", "random_arc", 8, "lanes"),
    # SWAR packed-word elementwise on BOTH sides (the XLA swar epilogue
    # vs the rr kernel's swar stages) — the round-6 headline candidate
    # shape plus the streaming form
    (1024, "off", "random", 1, "swar"),
    (2048, "on", "random_arc", 8, "swar"),
])
def test_full_round_equivalence_xla_vs_rr(block_c, rr_resident, topology,
                                          arc_align, elementwise):
    """The resident-round kernel (tick + view build + merge + reductions in
    ONE pallas call, with carried member counts and in-place lane update)
    reproduces the XLA scan bit-for-bit — states, carry, AND per-round
    metrics, across a deep horizon with churn and tracked crashes.

    block_c=1024 is the narrow resident stripe the N=65,536 capacity
    frontier runs (bench/frontier.py) — same kernel, 8x less VMEM per
    stripe; it admits the smaller n, which keeps the interpret-mode cost
    off the fast lane's critical path.  rr_resident="on" parks the TICKED
    lanes in VMEM and skips the receiver sweep's tick recompute (round-5
    floor-traffic mode) — pinned bit-identical to the streaming form and
    to XLA here."""
    base = SimConfig(
        n=4096 if block_c == 4096 else 2048,
        topology=topology,
        fanout=16 if arc_align > 1 else 6,
        arc_align=arc_align,
        remove_broadcast=False,
        fresh_cooldown=True,
        t_cooldown=12,
        view_dtype="int8",
        hb_dtype="int8",
        merge_block_c=block_c,
        rr_resident=rr_resident,
        elementwise=elementwise,
    )
    key = jax.random.PRNGKey(17)
    out = {}
    for kernel in ("xla", "pallas_rr_interpret"):
        cfg = dataclasses.replace(base, merge_kernel=kernel)
        final, carry, per_round = run_rounds(
            init_state(cfg), cfg, 8, key, crash_rate=0.02
        )
        out[kernel] = (final, carry, per_round)
    fx, cx, px = out["xla"]
    fp, cp, pp = out["pallas_rr_interpret"]
    assert jnp.array_equal(fx.hb, fp.hb)
    assert jnp.array_equal(fx.age, fp.age)
    assert jnp.array_equal(fx.status, fp.status)
    assert jnp.array_equal(fx.alive, fp.alive)
    assert jnp.array_equal(fx.hb_base, fp.hb_base)
    assert jnp.array_equal(cx.first_detect, cp.first_detect)
    assert jnp.array_equal(cx.first_observer, cp.first_observer)
    assert jnp.array_equal(cx.converged, cp.converged)
    assert jnp.array_equal(px.true_detections, pp.true_detections)
    assert jnp.array_equal(px.false_positives, pp.false_positives)


@pytest.mark.parametrize("topology,arc_align,fanout,elementwise", [
    # the round-11 fused SWIM lifecycle on the explicit-edge rr form
    ("random", 1, 6, "lanes"),
    # ... and on the production profile: aligned arcs + SWAR (the
    # capacity-ladder kernel config, ring-rotated build active)
    ("random_arc", 8, 16, "swar"),
])
def test_full_round_equivalence_xla_vs_rr_suspicion(topology, arc_align,
                                                    fanout, elementwise):
    """Round 11: suspicion armed on the resident-round kernel — SUSPECT
    entry/confirm fused into the packed tick, refute-on-advance fused
    into the merge epilogue, and the three suspicion reductions riding
    the kernel's per-subject outputs — must reproduce the XLA scan
    bit-for-bit: states, the full carry (first_suspect included) and the
    per-round metrics (suspects_entered / refutations / fp_suppressed)."""
    from gossipfs_tpu.suspicion import SuspicionParams

    base = SimConfig(
        n=2048, topology=topology, fanout=fanout, arc_align=arc_align,
        remove_broadcast=False, fresh_cooldown=True, t_cooldown=12,
        view_dtype="int8", hb_dtype="int8", merge_block_c=1024,
        rr_resident="on" if arc_align > 1 else "off",
        elementwise=elementwise, t_fail=3,
        suspicion=SuspicionParams(t_suspect=2),
    )
    key = jax.random.PRNGKey(17)
    out = {}
    for kernel in ("xla", "pallas_rr_interpret"):
        cfg = dataclasses.replace(base, merge_kernel=kernel)
        out[kernel] = run_rounds(
            init_state(cfg), cfg, 8, key, crash_rate=0.02
        )
    import numpy as np

    for a, b in zip(jax.tree.leaves(out["xla"]),
                    jax.tree.leaves(out["pallas_rr_interpret"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    per = out["xla"][2]
    assert int(jnp.sum(per.suspects_entered)) > 0  # lifecycle exercised
    assert int(jnp.sum(per.refutations)) > 0


@pytest.mark.slow  # interpreter-mode kernel rounds
@pytest.mark.parametrize("topology,rr_resident,arc_align,elementwise", [
    ("random", "off", 1, "lanes"),  # widened (int32) view stripe, c_blk=1024
    ("random_arc", "on", 1, "lanes"),  # resident lanes + window-maxed stripe
    # tile-aligned arc on an INT8 view stripe (c_blk=4096, cs=32): the
    # group max must run over the WRAPPED encodings — max-then-wrap picks
    # the wrong sender for deep-shift subjects whose rel straddles the
    # wrap (round-5 review finding; the bf16-stripe parity test above
    # cannot see it because widened stripes wrap rel before the max)
    ("random_arc", "on", 8, "lanes"),
    # the SWAR path in the same regime: its byte adds/subs wrap by
    # construction, which must reproduce the _wrap8 semantics exactly
    ("random", "off", 1, "swar"),
    ("random_arc", "on", 8, "swar"),
])
def test_rr_deep_shift_regime_parity(topology, rr_resident, arc_align,
                                     elementwise):
    """The shift_a < -128 regime (reachable after a rejoin drops a
    subject's base): the narrow XLA path computes its view encoding and
    merge compare in WRAPPING int8, and the rr kernel must reproduce that
    — an unwrapped i32 `lhs` made `advance` unconditionally true, and a
    widened view stripe stored rel - 256 (round-5 review findings, both
    fixed via merge_pallas._wrap8).  Synthetic state: deeply negative
    stored diagonal + large per-subject base drives shift_a ~ -245."""
    cfg = SimConfig(
        n=4096 if arc_align > 1 else 2048, topology=topology,
        fanout=16 if arc_align > 1 else 6, arc_align=arc_align,
        remove_broadcast=False,
        fresh_cooldown=True, t_cooldown=12, view_dtype="int8",
        hb_dtype="int8",
        merge_block_c=4096 if arc_align > 1 else 1024,
        rr_resident=rr_resident,
        elementwise=elementwise,
    )
    st = init_state(cfg)
    n = cfg.n
    hb = jnp.full((n, n), -125, jnp.int8).at[jnp.arange(n), jnp.arange(n)].set(-120)
    # basec=400 with stored diag -120: colmax_est = 281, view_base = 155,
    # shift_a = 155 - 400 = -245 < -128 (the V_SA_ALL regime); the -119
    # window top admits every lane here, all rel values wrap mod 256, and
    # the diagonal (at -120) beats the -125 receivers so the wrapped
    # merge compare must ADVANCE them — an unwrapped kernel instead drops
    # the whole view (rel-256 loses the max to the -1 sentinel) and
    # keeps, so the two formulations are distinguishable entry-by-entry
    st = st._replace(hb=hb, hb_base=jnp.full((n,), 400, jnp.int32))
    key = jax.random.PRNGKey(5)
    out = {}
    for kernel in ("xla", "pallas_rr_interpret"):
        c = dataclasses.replace(cfg, merge_kernel=kernel)
        out[kernel] = run_rounds(st, c, 3, key, crash_rate=0.01)
    fx, cx, px = out["xla"]
    fp, cp, pp = out["pallas_rr_interpret"]
    assert jnp.array_equal(fx.hb, fp.hb)
    assert jnp.array_equal(fx.age, fp.age)
    assert jnp.array_equal(fx.status, fp.status)
    assert jnp.array_equal(px.true_detections, pp.true_detections)
    assert jnp.array_equal(px.false_positives, pp.false_positives)


@pytest.mark.slow  # interpreter-mode kernel rounds
@pytest.mark.parametrize("elementwise", ["lanes", "swar"])
def test_rr_deep_shift_suspicion_parity(elementwise):
    """Round 11: the fused SUSPECT transitions in the shift_a < -128
    wrap regime.  The suspicion clock rides the age lane while the hb
    lane wraps mod 256 — the SUSPECT entry/confirm compares and the
    refute-on-advance must keep judging the WRAPPED int8 semantics the
    XLA narrow path computes (the deep-shift synthetic state from
    test_rr_deep_shift_regime_parity, with the lifecycle armed)."""
    from gossipfs_tpu.suspicion import SuspicionParams

    cfg = SimConfig(
        n=4096, topology="random_arc", fanout=16, arc_align=8,
        remove_broadcast=False, fresh_cooldown=True, t_cooldown=12,
        view_dtype="int8", hb_dtype="int8", merge_block_c=4096,
        rr_resident="on", elementwise=elementwise, t_fail=3,
        suspicion=SuspicionParams(t_suspect=2),
    )
    st = init_state(cfg)
    n = cfg.n
    hb = jnp.full((n, n), -125, jnp.int8).at[
        jnp.arange(n), jnp.arange(n)].set(-120)
    # same synthetic regime as the suspicion-free deep-shift case:
    # basec=400 with stored diag -120 drives shift_a ~ -245, every rel
    # wraps mod 256.  The -125 off-diagonal rows sit age-stale too, so
    # the first ticks push waves of entries through SUSPECT while the
    # wrapped advances refute them — both transitions exercised exactly
    # where the wrap semantics bind
    st = st._replace(hb=hb, hb_base=jnp.full((n,), 400, jnp.int32),
                     age=jnp.full((n, n), 3, jnp.int8))
    key = jax.random.PRNGKey(5)
    out = {}
    for kernel in ("xla", "pallas_rr_interpret"):
        c = dataclasses.replace(cfg, merge_kernel=kernel)
        out[kernel] = run_rounds(st, c, 4, key, crash_rate=0.01)
    import numpy as np

    for a, b in zip(jax.tree.leaves(out["xla"]),
                    jax.tree.leaves(out["pallas_rr_interpret"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    per = out["xla"][2]
    assert int(jnp.sum(per.suspects_entered)) > 0


def test_rr_rcnt_accumulated_form_matches_per_stripe():
    """The deep-stripe count form (rcnt_acc=True: per-stripe partials
    accumulate in a LANE-COMPACTED [N/LANE, LANE] VMEM scratch, flushed
    once at the final grid step — what the capacity frontier needs,
    where the per-stripe output would be a 3.4 GB side buffer; NOT
    lane-replicated, so reshape(n) IS the count vector) must produce the
    same lane outputs and the same reduced per-receiver counts as the
    default per-stripe form, on identical inputs."""
    import numpy as np

    from gossipfs_tpu.config import AGE_CLAMP
    from gossipfs_tpu.core.state import FAILED, MEMBER, UNKNOWN
    from gossipfs_tpu.ops import merge_pallas as mp

    n, c_blk, fanout = 1024, 512, 8
    nc, cs = n // c_blk, c_blk // mp.LANE
    key = jax.random.PRNGKey(3)
    ks = jax.random.split(key, 5)
    hb = jax.random.randint(ks[0], (nc, n, cs, mp.LANE), -128, 127, jnp.int8)
    age = jax.random.randint(ks[1], (nc, n, cs, mp.LANE), 1, 40, jnp.int32)
    st = jax.random.randint(ks[2], (nc, n, cs, mp.LANE), 0, 3, jnp.int32)
    asl = mp.pack_age_status(age, st)
    flags = jnp.broadcast_to(jnp.int8(1 + 4), (n, mp.LANE)).astype(jnp.int8)
    sa = jnp.zeros((nc, cs, mp.LANE), jnp.int32)
    sb = jnp.zeros((nc, cs, mp.LANE), jnp.int32)
    g = jnp.full((nc, cs, mp.LANE), -120, jnp.int32)
    bases = (jax.random.randint(ks[3], (n,), 0, n // 8, jnp.int32) * 8
             ).reshape(n, 1)
    kw = dict(fanout=fanout, member=int(MEMBER), unknown=int(UNKNOWN),
              failed=int(FAILED), age_clamp=AGE_CLAMP, window=126,
              t_fail=5, t_cooldown=12, block_r=128, arc_align=8,
              interpret=True)
    out_ps = mp.resident_round_blocked(bases, hb, asl, flags, sa, sb, g,
                                       rcnt_acc=False, **kw)
    out_ac = mp.resident_round_blocked(bases, hb, asl, flags, sa, sb, g,
                                       rcnt_acc=True, **kw)
    for a, b, name in zip(out_ps[:5], out_ac[:5],
                          ("hb", "asl", "cnt", "ndet", "fobs")):
        assert jnp.array_equal(a, b), name
    assert out_ps[5].shape == (n, nc * mp.LANE)
    assert out_ac[5].shape == (n // mp.LANE, mp.LANE)
    red_ps = np.asarray(
        jnp.sum(out_ps[5].reshape(n, -1), axis=1, dtype=jnp.int32)
        // mp.LANE)
    red_ac = np.asarray(out_ac[5].reshape(n)).astype(np.int32)
    np.testing.assert_array_equal(red_ps, red_ac)


def test_rr_lh_suspect_count_forms_match():
    """Round 14: the local-health lane's per-receiver SUSPECT-count
    output rides both recv_cnt forms (per-stripe partials and the
    lane-compacted accumulator) and must reduce identically; the
    degraded flag (bit 4) applies the stretched confirm threshold
    per ROW — rows with it set must differ from an un-flagged run
    exactly where SUSPECT entries sit between the two thresholds."""
    import numpy as np

    from gossipfs_tpu.config import AGE_CLAMP
    from gossipfs_tpu.core.state import FAILED, MEMBER, SUSPECT, UNKNOWN
    from gossipfs_tpu.ops import merge_pallas as mp

    n, c_blk, fanout = 1024, 512, 8
    nc, cs = n // c_blk, c_blk // mp.LANE
    key = jax.random.PRNGKey(9)
    ks = jax.random.split(key, 5)
    hb = jax.random.randint(ks[0], (nc, n, cs, mp.LANE), 2, 127, jnp.int8)
    age = jax.random.randint(ks[1], (nc, n, cs, mp.LANE), 1, 12, jnp.int32)
    st = jax.random.randint(ks[2], (nc, n, cs, mp.LANE), 0, 4, jnp.int32)
    asl = mp.pack_age_status(age, st)
    # rows [0, n/2) degraded (flags bit 4), the rest not — the per-row
    # threshold select must honor exactly this split
    fl = jnp.where(jnp.arange(n) < n // 2, jnp.int8(1 + 4 + 16),
                   jnp.int8(1 + 4))
    flags = jnp.broadcast_to(fl[:, None], (n, mp.LANE)).astype(jnp.int8)
    sa = jnp.zeros((nc, cs, mp.LANE), jnp.int32)
    sb = jnp.zeros((nc, cs, mp.LANE), jnp.int32)
    g = jnp.full((nc, cs, mp.LANE), -120, jnp.int32)
    bases = (jax.random.randint(ks[3], (n,), 0, n // 8, jnp.int32) * 8
             ).reshape(n, 1)
    kw = dict(fanout=fanout, member=int(MEMBER), unknown=int(UNKNOWN),
              failed=int(FAILED), age_clamp=AGE_CLAMP, window=126,
              t_fail=3, t_cooldown=12, block_r=128, arc_align=8,
              interpret=True, suspect=int(SUSPECT), t_suspect=2,
              lh_multiplier=3)
    out_ps = mp.resident_round_blocked(bases, hb, asl, flags, sa, sb, g,
                                       rcnt_acc=False, **kw)
    out_ac = mp.resident_round_blocked(bases, hb, asl, flags, sa, sb, g,
                                       rcnt_acc=True, **kw)
    assert len(out_ps) == 10 and len(out_ac) == 10
    for a, b, name in zip(out_ps[:5], out_ac[:5],
                          ("hb", "asl", "cnt", "ndet", "fobs")):
        assert jnp.array_equal(a, b), name

    def red(cnt):
        if cnt.size == n:
            return np.asarray(cnt.reshape(n)).astype(np.int32)
        return np.asarray(jnp.sum(cnt.reshape(n, -1), axis=1,
                                  dtype=jnp.int32) // mp.LANE)

    np.testing.assert_array_equal(red(out_ps[9]), red(out_ac[9]))
    # the suspect counts really count post-merge SUSPECT entries
    st_new = mp.unpack_age_status(out_ps[1])[1]
    want = np.asarray(jnp.sum((st_new == int(SUSPECT)).astype(jnp.int32),
                              axis=(0, 2, 3)))
    np.testing.assert_array_equal(red(out_ps[9]), want)
    # per-row stretch: degraded rows confirm LATER — rerun with no
    # degraded rows.  A stretched row holds its SUSPECT entries past
    # the base threshold instead of confirming them, so this round's
    # total confirmations strictly drop (and the held entries keep
    # gossiping, so the whole view — clean receivers included —
    # legitimately shifts; per-row isolation is NOT the invariant)
    flags0 = jnp.broadcast_to(jnp.int8(1 + 4), (n, mp.LANE)).astype(jnp.int8)
    out0 = mp.resident_round_blocked(bases, hb, asl, flags0, sa, sb, g,
                                     rcnt_acc=False, **kw)
    ndet_lh = int(np.asarray(out_ps[3]).sum())
    ndet_0 = int(np.asarray(out0[3]).sum())
    assert ndet_lh < ndet_0, (ndet_lh, ndet_0)
    # ...and the degraded rows hold MORE post-merge suspects than the
    # unstretched run left standing
    st0_new = mp.unpack_age_status(out0[1])[1]
    held0 = int(np.asarray(
        (st0_new[:, :n // 2] == int(SUSPECT)).sum()))
    held_lh = int(np.asarray(
        (st_new[:, :n // 2] == int(SUSPECT)).sum()))
    assert held_lh > held0, (held_lh, held0)


def test_stripe_and_arc_kernel_smoke():
    """Fast-lane coverage for the stripe/arc production kernels against
    the XLA round (the slow lane runs the deep 6-8 round versions above).

    The rr variant runs TWO rounds: single-round parity cannot catch bugs
    that only manifest on carried state — e.g. the in-place lane update
    feeding round 2 (ADVICE r5 #4).  The stripe variants stay at one
    round (no carried kernel state beyond the lanes themselves, and
    interpret-mode rounds at n=4096 are the lane's priciest seconds)."""
    for topology in ("random", "random_arc"):
        base = SimConfig(
            n=4096, topology=topology, fanout=6,
            remove_broadcast=False, fresh_cooldown=True,
            view_dtype="int8", hb_dtype="int8", merge_block_c=4096,
        )
        key = jax.random.PRNGKey(13)
        # the resident-round kernel (whole round in one pallas call, the
        # round-4 headline path) serves both random topologies: explicit
        # edges, or arc bases via the in-stripe windowed row-max.  The
        # rr-random pairing is covered by the deeper equivalence test
        # above, so the fast lane runs it only on the arc topology
        kernels = {"pallas_stripe_interpret": 1}
        if topology == "random_arc":
            kernels["pallas_rr_interpret"] = 2
        for kernel, rounds in kernels.items():
            out = {}
            for k in ("xla", kernel):
                cfg = dataclasses.replace(base, merge_kernel=k)
                out[k] = run_rounds(init_state(cfg), cfg, rounds, key,
                                    crash_rate=0.02)
            fx, cx, _ = out["xla"]
            fp, cp, _ = out[kernel]
            assert jnp.array_equal(fx.hb, fp.hb), (topology, kernel)
            assert jnp.array_equal(fx.status, fp.status), (topology, kernel)
            assert jnp.array_equal(cx.first_detect, cp.first_detect), (
                topology, kernel)


def _rr_tall_skinny_inputs(n, nloc, fanout, arc_align, seed=29):
    """Random packed-lane inputs at a [N rows x nloc local columns] shard
    shape — rows >> columns, the sharded capacity regime the square tests
    never exercise (and where the row budget binds)."""
    from gossipfs_tpu.ops import merge_pallas as mp

    c_blk = 512
    nc, cs = nloc // c_blk, c_blk // mp.LANE
    ks = jax.random.split(jax.random.PRNGKey(seed), 5)
    hb = jax.random.randint(ks[0], (nc, n, cs, mp.LANE), -128, 127, jnp.int8)
    age = jax.random.randint(ks[1], (nc, n, cs, mp.LANE), 1, 40, jnp.int32)
    st = jax.random.randint(ks[2], (nc, n, cs, mp.LANE), 0, 3, jnp.int32)
    asl = mp.pack_age_status(age, st)
    fl = jnp.where(jax.random.uniform(ks[3], (n,)) > 0.1, 5, 4).astype(jnp.int8)
    flags = fl.reshape(n // mp.LANE, mp.LANE)  # LANE-compacted layout
    sa = jnp.zeros((nc, cs, mp.LANE), jnp.int32)
    sb = jnp.zeros((nc, cs, mp.LANE), jnp.int32)
    g = jnp.full((nc, cs, mp.LANE), -120, jnp.int32)
    bases = (jax.random.randint(ks[4], (n,), 0, n // arc_align, jnp.int32)
             * arc_align).reshape(n, 1)
    return hb, asl, flags, sa, sb, g, bases


def test_rr_ring_rotated_tall_skinny_shards_match_full():
    """The ring-rotated view build + LANE-compacted flags at TALL-SKINNY
    shard shapes (rows >> columns — the sharded capacity regime where the
    row budget binds, which the square-shape tests never exercise): each
    shard's [N x nloc] program, run with its global column offset, must
    reproduce the corresponding stripes of the full single-chip run
    bit-for-bit — lanes, per-subject reductions, and the per-receiver
    count partials.  The full run is itself oracle-pinned by the XLA
    parity tests above, so shard == full implies shard == oracle."""
    from gossipfs_tpu.config import AGE_CLAMP
    from gossipfs_tpu.core.state import FAILED, MEMBER, UNKNOWN
    from gossipfs_tpu.ops import merge_pallas as mp

    n, fanout, align, shards = 2048, 16, 8, 4
    nloc = n // shards  # 512 local columns against 2048 rows (4:1)
    hb, asl, flags, sa, sb, g, bases = _rr_tall_skinny_inputs(
        n, n, fanout, align)
    kw = dict(fanout=fanout, member=int(MEMBER), unknown=int(UNKNOWN),
              failed=int(FAILED), age_clamp=AGE_CLAMP, window=126,
              t_fail=5, t_cooldown=12, block_r=128, arc_align=align,
              resident=True, interpret=True)
    full = mp.resident_round_blocked(bases, hb, asl, flags, sa, sb, g, **kw)
    npc = nloc // 512  # stripes per shard
    for s in range(shards):
        sl = slice(s * npc, (s + 1) * npc)
        shard = mp.resident_round_blocked(
            bases, hb[sl], asl[sl], flags, sa[sl], sb[sl], g[sl],
            col_offset=s * nloc, **kw)
        for k, name in ((0, "hb"), (1, "asl"), (2, "cnt"), (3, "ndet")):
            assert jnp.array_equal(shard[k], full[k][sl]), (s, name)
        # fobs is per-subject (column-indexed): the shard's values are the
        # full run's for its columns
        assert jnp.array_equal(shard[4], full[4][sl]), (s, "fobs")
        # per-stripe count partials: the shard's rcnt block is the full
        # run's column block for its stripes
        assert jnp.array_equal(
            shard[5], full[5][:, s * npc * mp.LANE:(s + 1) * npc * mp.LANE]
        ), (s, "rcnt")


def test_rr_rotate_and_flags_layouts_bit_equal():
    """A/B over the round-9 layouts at a tall-skinny shard shape: the
    ring-rotated build vs the full-T fallback (rotate=False), and the
    LANE-compacted vs lane-replicated flags input, must all produce
    identical outputs — detection semantics stay bit-identical while the
    hot path's VMEM row cost collapses."""
    from gossipfs_tpu.config import AGE_CLAMP
    from gossipfs_tpu.core.state import FAILED, MEMBER, UNKNOWN
    from gossipfs_tpu.ops import merge_pallas as mp

    n, nloc, fanout, align = 2048, 512, 16, 8
    hb, asl, flags, sa, sb, g, bases = _rr_tall_skinny_inputs(
        n, nloc, fanout, align)
    kw = dict(fanout=fanout, member=int(MEMBER), unknown=int(UNKNOWN),
              failed=int(FAILED), age_clamp=AGE_CLAMP, window=126,
              t_fail=5, t_cooldown=12, block_r=128, arc_align=align,
              resident=True, col_offset=512, interpret=True)
    want = mp.resident_round_blocked(bases, hb, asl, flags, sa, sb, g,
                                     rotate=True, **kw)
    names = ("hb", "asl", "cnt", "ndet", "fobs", "rcnt")
    # full-T + replicated-flags fallback layouts (the rotate=False probe
    # fallback bench.py keeps for on-chip regressions)
    got = mp.resident_round_blocked(bases, hb, asl, flags, sa, sb, g,
                                    rotate=False, **kw)
    for a, b, name in zip(got, want, names):
        assert jnp.array_equal(a, b), f"rotate=False {name}"
    # legacy lane-replicated flags input (the wrapper compacts it)
    flags_rep = jnp.broadcast_to(flags.reshape(n, 1), (n, mp.LANE))
    got = mp.resident_round_blocked(bases, hb, asl, flags_rep, sa, sb, g,
                                    rotate=True, **kw)
    for a, b, name in zip(got, want, names):
        assert jnp.array_equal(a, b), f"replicated flags {name}"
    # swar over the ring build at the same shard shape
    got = mp.resident_round_blocked(bases, hb, asl, flags, sa, sb, g,
                                    rotate=True, elementwise="swar", **kw)
    for a, b, name in zip(got, want, names):
        assert jnp.array_equal(a, b), f"swar ring {name}"


def test_rr_scratch_budget_lint():
    """Reconcile rr_align_scratch_bytes against the kernel's ACTUAL pltpu
    scratch allocations (and the flags input block against the bytes
    rr_flags_bytes charges), so the budget math can never silently drift
    from the kernel again — plus the rotated row-budget acceptance
    shapes (>= 512k rows at c_blk=512; the round-5 layouts still
    rejected).

    Round 15: the reconciliation itself migrated to the gossipfs-lint
    registry (gossipfs_tpu/analysis/probes.py, the rr-scratch-budget
    probe rule — ``tools/lint.py --probe`` runs it outside pytest, and
    its drift-injection fixture lives in tests/fixtures/lint/).  This
    wrapper keeps the enforcement at its historical home on the fast
    lane; every assertion above survives as a probe finding."""
    from gossipfs_tpu.analysis import probes

    findings = probes.check_rr_scratch_budget(None)
    assert not findings, "\n".join(str(f) for f in findings)


@pytest.mark.parametrize("topology,rr_resident,arc_align", [
    ("random", "off", 1),
    ("random", "on", 1),
    ("random_arc", "on", 8),
])
def test_rr_swar_matches_lanes_multi_round(topology, rr_resident, arc_align):
    """Fast lane: the SWAR packed-word elementwise path
    (config.elementwise="swar", ops/swar.py) is bit-equal to the widened
    lanes path through the resident-round kernel over a MULTI-ROUND scan
    with crash churn — states, metrics carry, and per-round metrics.
    Multi-round matters: the carried in-place lanes feed round 2+, and
    detections/cooldowns only cross the threshold compares after a few
    rounds of aging.  Small n keeps the interpret-mode cost off the fast
    lane's critical path; the slow lane runs the n=2048/4096 XLA-oracle
    versions above with a swar case in the parameter grid."""
    base = SimConfig(
        n=1024, topology=topology, fanout=16 if arc_align > 1 else 6,
        arc_align=arc_align, remove_broadcast=False, fresh_cooldown=True,
        t_cooldown=12, view_dtype="int8", hb_dtype="int8",
        merge_kernel="pallas_rr_interpret", merge_block_c=512,
        rr_resident=rr_resident,
    )
    key = jax.random.PRNGKey(23)
    out = {}
    for ew in ("lanes", "swar"):
        cfg = dataclasses.replace(base, elementwise=ew)
        out[ew] = run_rounds(init_state(cfg), cfg, 6, key, crash_rate=0.03)
    (fl, cl, pl_), (fs, cs_, ps) = out["lanes"], out["swar"]
    for name in ("hb", "age", "status", "alive", "hb_base"):
        assert jnp.array_equal(getattr(fl, name), getattr(fs, name)), name
    assert jnp.array_equal(cl.first_detect, cs_.first_detect)
    assert jnp.array_equal(cl.first_observer, cs_.first_observer)
    assert jnp.array_equal(cl.converged, cs_.converged)
    assert jnp.array_equal(pl_.true_detections, ps.true_detections)
    assert jnp.array_equal(pl_.false_positives, ps.false_positives)
