"""Pallas merge kernel: interpret-mode equivalence against the XLA oracle.

The kernel (ops/merge_pallas.py) must be bit-identical to the XLA gather
formulation — the golden-parity suite pins the XLA path to the reference
protocol, so kernel == oracle implies kernel == reference.  These tests run
the kernel in interpreter mode on CPU; the real-TPU timing lives in bench.py.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import pytest

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.core.rounds import run_rounds
from gossipfs_tpu.core.state import init_state
from gossipfs_tpu.ops.merge_pallas import (
    fanout_max_merge,
    fanout_max_merge_xla,
    supported,
)


@pytest.mark.parametrize("dtype", [jnp.int32, jnp.int16, jnp.int8])
@pytest.mark.parametrize("n,fanout", [(128, 3), (256, 8), (384, 17)])
def test_kernel_matches_oracle(n, fanout, dtype):
    key = jax.random.PRNGKey(n + fanout)
    k1, k2 = jax.random.split(key)
    # int16/int8 are the production view dtypes (core/rounds.py rebases
    # heartbeats into config.view_dtype); int32 keeps the kernel dtype-generic
    view = jax.random.randint(k1, (n, n), -1, 100, dtype=jnp.int32).astype(dtype)
    edges = jax.random.randint(k2, (n, fanout), 0, n, dtype=jnp.int32)
    got = fanout_max_merge(view, edges, interpret=True)
    want = fanout_max_merge_xla(view, edges)
    assert got.dtype == dtype
    assert jnp.array_equal(got, want)


def test_kernel_blocks_smaller_than_defaults():
    # N smaller than the default block sizes: blocks must shrink to fit
    n, fanout = 128, 4
    view = jax.random.randint(jax.random.PRNGKey(0), (n, n), -1, 50, jnp.int32)
    edges = jax.random.randint(jax.random.PRNGKey(1), (n, fanout), 0, n, jnp.int32)
    got = fanout_max_merge(
        view, edges, block_r=512, block_c=8192, slots=8, interpret=True
    )
    assert jnp.array_equal(got, fanout_max_merge_xla(view, edges))


def test_unsupported_shapes_rejected():
    assert not supported(100, 3)  # not lane-aligned
    assert supported(256, 3)
    view = jnp.zeros((100, 100), dtype=jnp.int32)
    edges = jnp.zeros((100, 3), dtype=jnp.int32)
    with pytest.raises(ValueError, match="XLA path"):
        fanout_max_merge(view, edges, interpret=True)


def test_full_round_equivalence_xla_vs_pallas():
    """run_rounds with merge_kernel=pallas_interpret reproduces the XLA
    scan bit-for-bit (states, detection rounds, per-round metrics)."""
    base = SimConfig(
        n=128,
        topology="random",
        fanout=5,
        remove_broadcast=False,
        fresh_cooldown=True,
    )
    key = jax.random.PRNGKey(7)
    out = {}
    for kernel in ("xla", "pallas_interpret"):
        cfg = dataclasses.replace(base, merge_kernel=kernel)
        state = init_state(cfg)
        final, carry, per_round = run_rounds(
            state, cfg, 12, key, crash_rate=0.02, rejoin_rate=0.01
        )
        out[kernel] = (final, carry, per_round)

    fx, cx, px = out["xla"]
    fp, cp, pp = out["pallas_interpret"]
    assert jnp.array_equal(fx.hb, fp.hb)
    assert jnp.array_equal(fx.age, fp.age)
    assert jnp.array_equal(fx.status, fp.status)
    assert jnp.array_equal(fx.alive, fp.alive)
    assert jnp.array_equal(cx.first_detect, cp.first_detect)
    assert jnp.array_equal(cx.converged, cp.converged)
    assert jnp.array_equal(px.true_detections, pp.true_detections)
    assert jnp.array_equal(px.false_positives, pp.false_positives)
