"""Zombie-rejoin corner: diagonal-anchored rebase kills it (VERDICT #7).

Round 1 deferred two related corners (PARITY.md):

* int16 storage: the per-subject store base was MONOTONE, so a node
  rejoining after the base had climbed past 32768 (reachable within the
  soak's own horizon) had its fresh hb=0 entries clamp to the floor
  sentinel — permanently out of gossip and detection ("per-incarnation
  lifetime bound").
* int8 view: a rejoin while zombie MEMBER copies of the old incarnation
  (counters > the 126-round window ahead) survive anchored the view base
  on the zombies, clamping the fresh entries out of the gossip view.

The diagonal-anchored rebase (core/rounds._pre_tick) resolves both: the
base follows the subject's OWN counter — down included — so a rejoin
resets it; old-incarnation lanes renormalize above the window, are
excluded from gossip by the view clamp, and age out at their holders.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.core.rounds import run_rounds
from gossipfs_tpu.core.state import MEMBER, RoundEvents, init_state

KEY = jax.random.PRNGKey(4)


def scheduled(n, rounds, crash_at=None, crash=(), join_at=None, join=()):
    c = np.zeros((rounds, n), dtype=bool)
    j = np.zeros((rounds, n), dtype=bool)
    if crash_at is not None:
        c[crash_at, list(crash)] = True
    if join_at is not None:
        j[join_at, list(join)] = True
    z = jnp.zeros((rounds, n), dtype=bool)
    return RoundEvents(crash=jnp.asarray(c), leave=z, join=jnp.asarray(j))


@pytest.mark.parametrize("base_val", [40_000, 60_000])
def test_int16_rejoin_under_high_base_recovers(base_val):
    """The permanent round-1 corner: rejoin with the store base past the
    int16 floor's reach.  The state is constructed as a run ~40k/60k rounds
    in (true counters = base_val, stored relative to base_val - window).
    base_val=60,000 puts the base itself beyond 32,768 — the regime where
    the hz join-encoding saturates and only the join-time column rebase
    (core/rounds._apply_events) keeps the fresh incarnation representable;
    the old monotone base bricked such rejoins permanently."""
    from gossipfs_tpu.config import REBASE_WINDOW

    n = 16
    cfg = SimConfig(
        n=n, topology="random", fanout=4, remove_broadcast=False,
        fresh_cooldown=True, t_cooldown=12, view_dtype="int8",
        hb_dtype="int16",
    )
    state = init_state(cfg)
    state = state._replace(
        # true counter = stored + base = 40,000 for every entry
        hb=jnp.full_like(state.hb, REBASE_WINDOW - 1),
        hb_base=jnp.full_like(state.hb_base, base_val - (REBASE_WINDOW - 1)),
    )
    assert int(np.asarray(state.hb_true())[0, 0]) == base_val

    # crash node 5, let detection + cooldown fully expire its old entries
    state, _, _ = run_rounds(
        state, cfg, 25, KEY, events=scheduled(n, 25, crash_at=0, crash=[5])
    )
    assert not bool(np.asarray(state.alive)[5])
    # rejoin: the new incarnation starts at hb 0, ~40k below the old base
    state, _, _ = run_rounds(
        state, cfg, 30, KEY, events=scheduled(n, 30, join_at=0, join=[5])
    )
    status = np.asarray(state.status)
    true_hb = np.asarray(state.hb_true())
    assert bool(np.asarray(state.alive)[5])
    # the base followed the diagonal down
    assert int(np.asarray(state.hb_base)[5]) == 0
    for obs in range(n):
        assert status[obs, 5] == int(MEMBER), f"observer {obs} lost node 5"
        # fresh-incarnation counters (~30 bumps), not sentinels, not zombies
        assert 1 <= true_hb[obs, 5] <= 60, (obs, true_hb[obs, 5])
    # dissemination is live gossip, not just the introducer's one-shot push
    assert true_hb[1, 5] >= true_hb[5, 5] - 15


def test_int8_view_rejoin_while_zombie_member_copies_live():
    """The transient view corner: rejoin a few rounds after the crash,
    while the holders' MEMBER copies still carry the old incarnation's
    counter (> window ahead of the fresh hb=0).  The view base must follow
    the fresh incarnation immediately — the zombies must neither clamp the
    fresh entries out of gossip nor resurrect the old counter."""
    n = 16
    cfg = SimConfig(
        n=n, topology="random", fanout=4, remove_broadcast=False,
        fresh_cooldown=True, t_cooldown=12, view_dtype="int8",
    )
    state = init_state(cfg)
    # 200 quiet rounds: counters ~200, beyond the 126-round int8 window
    state, _, _ = run_rounds(state, cfg, 200, KEY)
    assert int(np.asarray(state.hb_true())[0, 0]) > 130
    # crash 5, rejoin 3 rounds later — before detection (t_fail=5) fires,
    # so every holder still has a MEMBER zombie copy at ~200
    ev = scheduled(n, 40, crash_at=0, crash=[5], join_at=3, join=[5])
    state, _, _ = run_rounds(state, cfg, 40, KEY, events=ev)
    status = np.asarray(state.status)
    true_hb = np.asarray(state.hb_true())
    assert bool(np.asarray(state.alive)[5])
    for obs in range(n):
        assert status[obs, 5] == int(MEMBER), f"observer {obs} lost node 5"
        # fresh incarnation's counter (< 40), not the ~200 zombie value
        assert 1 <= true_hb[obs, 5] <= 60, (obs, true_hb[obs, 5])


def test_zombie_copies_cannot_readd_dead_node():
    """Zombie values are clamped out of the gossip view entirely: stale
    copies of a long-dead node can never re-add it."""
    n = 16
    cfg = SimConfig(
        n=n, topology="random", fanout=4, remove_broadcast=False,
        fresh_cooldown=True, t_cooldown=12, view_dtype="int8",
    )
    state = init_state(cfg)
    state, _, _ = run_rounds(state, cfg, 200, KEY)
    dead = [x for x in range(n) if x not in (0, 1, 2, 3)]
    state, _, _ = run_rounds(
        state, cfg, 60, KEY, events=scheduled(n, 60, crash_at=0, crash=dead)
    )
    status = np.asarray(state.status)
    for obs in (0, 1, 2, 3):
        for subj in dead:
            assert status[obs, subj] != int(MEMBER), (obs, subj)


def test_int8_storage_rejoin_under_high_base_recovers():
    """Same corner for the all-int8 storage mode (hb_dtype='int8'): the
    tiny 126-round window makes deep bases routine, so the join-time
    column rebase is load-bearing from the first few hundred rounds."""
    from gossipfs_tpu.config import INT8_REBASE_WINDOW

    n = 16
    cfg = SimConfig(
        n=n, topology="random", fanout=4, remove_broadcast=False,
        fresh_cooldown=True, t_cooldown=12, view_dtype="int8",
        hb_dtype="int8",
    )
    base_val = 40_000
    state = init_state(cfg)
    state = state._replace(
        hb=jnp.full_like(state.hb, INT8_REBASE_WINDOW - 1),
        hb_base=jnp.full_like(
            state.hb_base, base_val - (INT8_REBASE_WINDOW - 1)
        ),
    )
    assert int(np.asarray(state.hb_true())[0, 0]) == base_val
    state, _, _ = run_rounds(
        state, cfg, 25, KEY, events=scheduled(n, 25, crash_at=0, crash=[5])
    )
    state, _, _ = run_rounds(
        state, cfg, 30, KEY, events=scheduled(n, 30, join_at=0, join=[5])
    )
    status = np.asarray(state.status)
    true_hb = np.asarray(state.hb_true())
    assert bool(np.asarray(state.alive)[5])
    assert int(np.asarray(state.hb_base)[5]) == 0
    for obs in range(n):
        assert status[obs, 5] == int(MEMBER), f"observer {obs} lost node 5"
        assert 1 <= true_hb[obs, 5] <= 60, (obs, true_hb[obs, 5])
