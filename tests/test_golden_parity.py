"""Golden-trace equivalence: tensor kernel vs naive per-node Python model.

The vectorized round kernel must reproduce the object-style oracle
(tests/reference_model.py) entry-for-entry on every alive node's table, every
round, under crashes, leaves, joins and both topologies — the sim-level
analogue of diffing against the Go implementation's wire behavior (SURVEY §4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.core.rounds import gossip_round
from gossipfs_tpu.core.state import RoundEvents, init_state
from gossipfs_tpu.core.topology import random_in_edges
from reference_model import NaiveSim


def masks_to_lists(ev: RoundEvents):
    return (
        [int(j) for j in np.nonzero(np.array(ev.crash))[0]],
        [int(j) for j in np.nonzero(np.array(ev.leave))[0]],
        [int(j) for j in np.nonzero(np.array(ev.join))[0]],
    )


def run_both(cfg, rounds, events_by_round, member_mask=None, seed=0):
    state = init_state(cfg, member_mask=member_mask)
    naive = NaiveSim(cfg, member_mask=None if member_mask is None else np.array(member_mask))
    key = jax.random.PRNGKey(seed)
    for r in range(rounds):
        ev = events_by_round.get(r, RoundEvents.none(cfg.n))
        k = jax.random.fold_in(key, r)
        if cfg.topology == "random":
            edges = np.array(random_in_edges(k, cfg.n, cfg.fanout))
            state, _, _, _ = gossip_round(state, ev, jnp.asarray(edges), cfg)
        else:
            edges = None
            state, _, _, _ = gossip_round(state, ev, None, cfg)
        crash, leave, join = masks_to_lists(ev)
        naive.step(edges, crash=crash, leave=leave, join=join)
        compare(state, naive, where=f"round {r}")
    return state, naive


def compare(state, naive, where):
    n = state.n
    alive_vec = np.array(state.alive)
    assert alive_vec.tolist() == naive.alive, f"alive mismatch @ {where}"
    hb = np.array(state.hb)
    age = np.array(state.age)
    status = np.array(state.status)
    for i in range(n):
        if not naive.alive[i]:
            continue  # dead processes don't run; their rows are unspecified
        for j in range(n):
            e = naive.tables[i][j]
            assert status[i][j] == e.status, f"status[{i},{j}] @ {where}"
            if e.status != 0:
                assert hb[i][j] == e.hb, f"hb[{i},{j}] @ {where}"
                assert age[i][j] == e.age, f"age[{i},{j}] @ {where}"


def ev(n, crash=(), leave=(), join=()):
    def m(idx):
        a = np.zeros(n, dtype=bool)
        a[list(idx)] = True
        return jnp.asarray(a)

    return RoundEvents(crash=m(crash), leave=m(leave), join=m(join))


class TestGoldenParity:
    def test_ring_steady_and_crash(self):
        cfg = SimConfig(n=12)
        run_both(cfg, 25, {8: ev(12, crash=[3])})

    def test_ring_multi_crash_and_leave(self):
        cfg = SimConfig(n=14)
        run_both(cfg, 30, {6: ev(14, crash=[2, 9]), 12: ev(14, leave=[5])})

    def test_rejoin_after_cooldown(self):
        cfg = SimConfig(n=12)
        run_both(cfg, 35, {5: ev(12, crash=[7]), 25: ev(12, join=[7])})

    def test_join_of_fresh_node(self):
        cfg = SimConfig(n=12)
        mask = jnp.arange(12) < 9
        run_both(cfg, 25, {4: ev(12, join=[10])}, member_mask=mask)

    def test_simultaneous_leave_and_crash(self):
        cfg = SimConfig(n=12)
        run_both(cfg, 25, {7: ev(12, crash=[1], leave=[2])})

    def test_random_topology(self):
        cfg = SimConfig(n=16, topology="random", fanout=4)
        run_both(cfg, 30, {9: ev(16, crash=[11])}, seed=3)

    def test_no_remove_broadcast(self):
        cfg = SimConfig(n=12, remove_broadcast=False)
        run_both(cfg, 30, {8: ev(12, crash=[3])})

    def test_small_group_refresh_only(self):
        cfg = SimConfig(n=8)
        mask = jnp.arange(8) < 3
        run_both(cfg, 20, {5: ev(8, crash=[1])}, member_mask=mask)

    def test_introducer_crash_then_join_attempt(self):
        cfg = SimConfig(n=12)
        mask = jnp.arange(12) < 10
        run_both(cfg, 25, {3: ev(12, crash=[0]), 8: ev(12, join=[11])}, member_mask=mask)
