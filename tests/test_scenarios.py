"""Scenario engine: declarative partitions, link faults and slow nodes
driven through the three transport engines from one schedule
(gossipfs_tpu/scenarios/ — see ISSUE/BASELINE "scenario engine").

Fast lane: schema + runtime semantics, the tensor engine's edge filter
(zero cross-partition propagation, heal/reconvergence, loss and slow
rules), sim-vs-UDP parity on the same scenario file, the CoSim quorum
story under a minority-side partition, literal-N padding exclusion, and
the CLI verbs.  Slow lane: the per-process deployment variant.
"""

import asyncio
import io
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.core.state import MEMBER, SimState, init_state
from gossipfs_tpu.scenarios import (
    FaultScenario,
    LinkFault,
    Partition,
    ScenarioRuntime,
    SlowNode,
    compile_tensor,
    require_scenario_config,
    split_halves,
    xla_fallback_config,
)

pytestmark = pytest.mark.scenario


def gossip_only_cfg(n: int, **over) -> SimConfig:
    kw = dict(
        n=n, topology="random", fanout=SimConfig.log_fanout(n),
        remove_broadcast=False, fresh_cooldown=True, t_cooldown=6,
    )
    kw.update(over)
    return SimConfig(**kw)


# ---------------------------------------------------------------------------
# schema + runtime semantics
# ---------------------------------------------------------------------------


class TestSchema:
    def test_json_roundtrip_all_rule_kinds(self):
        sc = FaultScenario(
            name="kitchen-sink", n=64, seed=3,
            partitions=(Partition(start=2, end=9,
                                  groups=(tuple(range(16)),
                                          tuple(range(16, 32)))),),
            link_faults=(LinkFault(start=0, end=5, rate=0.25,
                                   src=tuple(range(64)), dst=(7, 9)),),
            slow_nodes=(SlowNode(start=1, end=20, stride=4,
                                 nodes=tuple(range(8, 16))),),
        )
        rt = FaultScenario.from_json(sc.to_json())
        assert rt == sc
        assert rt.horizon == 20
        assert rt.active_at(4) and not rt.active_at(25)
        assert len(rt.active_rules(2)) == 3

    def test_selectors(self):
        doc = """{"name": "s", "n": 8, "partitions": [
            {"start": 0, "end": 4,
             "groups": [{"range": [0, 3]}, [5, 6]]}],
            "link_faults": [
            {"start": 0, "end": 2, "rate": 1.0, "src": "all", "dst": [0]}]}"""
        sc = FaultScenario.from_json(doc)
        assert sc.partitions[0].groups == ((0, 1, 2), (5, 6))
        assert sc.link_faults[0].src == tuple(range(8))
        # pid: groups -> 1, 2; the rest (3, 4, 7) -> implicit 0
        assert sc.pid_at(1).tolist() == [1, 1, 1, 0, 0, 2, 2, 0]
        assert sc.pid_at(4) is None

    def test_validation(self):
        with pytest.raises(ValueError, match="overlap"):
            FaultScenario(name="x", n=8, partitions=(
                Partition(start=0, end=2, groups=((0, 1), (1, 2))),))
        with pytest.raises(ValueError, match="out of range"):
            FaultScenario(name="x", n=8, partitions=(
                Partition(start=0, end=2, groups=((9,),)),))
        with pytest.raises(ValueError, match="rate"):
            FaultScenario(name="x", n=8, link_faults=(
                LinkFault(start=0, end=2, rate=1.5, src=(0,), dst=(1,)),))
        with pytest.raises(ValueError, match="stride"):
            FaultScenario(name="x", n=8, slow_nodes=(
                SlowNode(start=0, end=2, stride=1, nodes=(0,)),))
        with pytest.raises(ValueError, match="start < end"):
            FaultScenario(name="x", n=8, partitions=(
                Partition(start=5, end=5, groups=((0,),)),))

    def test_runtime_drop_semantics(self):
        sc = FaultScenario(
            name="rt", n=6,
            partitions=(Partition(start=2, end=5, groups=((0, 1, 2),)),),
            link_faults=(LinkFault(start=0, end=10, rate=1.0,
                                   src=(4,), dst=(5,)),),
            slow_nodes=(SlowNode(start=0, end=10, stride=3, nodes=(3,)),),
        )
        rt = ScenarioRuntime(sc)
        # partition only inside its window
        assert rt.drops(0, 4, 3) and rt.drops(4, 0, 3)
        assert not rt.drops(0, 4, 1) and not rt.drops(0, 4, 5)
        # total directional loss = asymmetric link: 4->5 dead, 5->4 alive
        assert rt.drops(4, 5, 0) and not rt.drops(5, 4, 0)
        # slow node: messages only get out on stride multiples
        assert not rt.drops(3, 0, 0) and not rt.drops(3, 0, 6)
        assert rt.drops(3, 0, 1) and rt.drops(3, 0, 7)

    def test_gating(self):
        broadcast = SimConfig(n=16)  # reference mode: remove_broadcast on
        with pytest.raises(ValueError, match="remove_broadcast"):
            require_scenario_config(broadcast)
        arc = SimConfig(n=1024, topology="random_arc", fanout=10,
                        remove_broadcast=False, fresh_cooldown=True)
        with pytest.raises(ValueError, match="random_arc"):
            require_scenario_config(arc)
        # the fallback keeps the protocol, swaps only the merge kernel
        fast = gossip_only_cfg(2048, merge_kernel="pallas",
                               view_dtype="int8", hb_dtype="int16",
                               merge_block_c=1024)
        fell = xla_fallback_config(fast)
        assert fell.merge_kernel == "xla"
        assert (fell.t_fail, fell.hb_dtype, fell.view_dtype) == (
            fast.t_fail, fast.hb_dtype, fast.view_dtype)


# ---------------------------------------------------------------------------
# gray-failure primitives (round 13: flapping + correlated outages)
# ---------------------------------------------------------------------------


class TestGrayFailurePrimitives:
    def _scenario(self, n=16):
        from gossipfs_tpu.scenarios import CorrelatedOutage, Flapping

        return FaultScenario(
            name="gray", n=n,
            flapping=(Flapping(start=2, end=20, up=3, down=4,
                               nodes=(1, 2)),),
            outages=(CorrelatedOutage(start=5, end=9, nodes=(8, 9, 10)),),
        )

    def test_runtime_drop_semantics(self):
        """Reference semantics (scenarios/runtime.py): flapping mutes a
        node's OUTGOING datagrams on its duty cycle's dark phase only;
        an outage group talks to no one — itself included — for the
        window, both directions."""
        rt = ScenarioRuntime(self._scenario())
        # flap cycle from start=2: rounds 2,3,4 up; 5,6,7,8 dark; 9+ up
        assert not rt.drops(1, 0, 2) and not rt.drops(1, 0, 4)
        assert rt.drops(1, 0, 5) and rt.drops(2, 0, 8)
        assert not rt.drops(1, 0, 9) and not rt.drops(1, 0, 11)
        assert rt.drops(1, 0, 12)      # next cycle's dark phase
        assert not rt.drops(0, 1, 5)   # inbound to a dark flapper flows
        assert not rt.drops(1, 0, 25)  # window over: healthy
        # outage: both directions AND intra-group (the switch died)
        assert rt.drops(8, 0, 5) and rt.drops(0, 8, 5)
        assert rt.drops(8, 9, 6)
        assert not rt.drops(8, 0, 4) and not rt.drops(8, 0, 9)

    def test_json_roundtrip_and_queries(self):
        sc = self._scenario()
        assert FaultScenario.from_json(sc.to_json()) == sc
        assert sc.horizon == 20
        assert sc.active_at(3) and not sc.active_at(20)
        # unreachable_at: outage members always; flappers dark-phase only
        assert sc.unreachable_at(5) == {1, 2, 8, 9, 10}
        assert sc.unreachable_at(3) == set()
        assert sc.unreachable_at(10) == set()
        rules = sc.active_rules(6)
        assert any("flap" in r and "DARK" in r for r in rules)
        assert any("outage" in r for r in rules)

    def test_validation(self):
        from gossipfs_tpu.scenarios import CorrelatedOutage, Flapping

        with pytest.raises(ValueError, match="up >= 1"):
            FaultScenario(name="x", n=8, flapping=(
                Flapping(start=0, end=4, up=0, down=2, nodes=(1,)),))
        with pytest.raises(ValueError, match="down >= 1"):
            FaultScenario(name="x", n=8, flapping=(
                Flapping(start=0, end=4, up=2, down=0, nodes=(1,)),))
        with pytest.raises(ValueError, match="empty outage"):
            FaultScenario(name="x", n=8, outages=(
                CorrelatedOutage(start=0, end=4, nodes=()),))
        with pytest.raises(ValueError, match="out of range"):
            FaultScenario(name="x", n=8, outages=(
                CorrelatedOutage(start=0, end=4, nodes=(9,)),))

    def test_tensor_matches_runtime_per_edge(self):
        """The compiled rule table drops exactly the (src, dst, round)
        triples the per-message reference drops — flapping and outages
        included (the round-7 parity argument extended)."""
        from gossipfs_tpu.scenarios.tensor import filter_edges

        sc = self._scenario()
        rt = ScenarioRuntime(sc)
        tsc = compile_tensor(sc)
        n = sc.n
        key = jax.random.PRNGKey(0)
        edges = jnp.tile(jnp.arange(n, dtype=jnp.int32)[None, :], (n, 1))
        for rnd in range(22):
            out = np.asarray(filter_edges(tsc, edges, jnp.int32(rnd), key))
            for i in range(n):
                for j in range(n):
                    if i == j:
                        continue
                    assert (out[i, j] == i) == rt.drops(j, i, rnd), (
                        i, j, rnd)

    def test_flap_and_outage_ride_aligned_arcs_loss_rejected(self):
        """Capability matrix (round 14): flapping is sender-global
        (rides the aligned-arc sends_mask like slow nodes); a correlated
        outage is separable into a sender-global mute (sends_mask) plus
        a receiver-global zero match mask (arc_match_edges) — accepted
        on aligned arcs with EXACT per-edge semantics and no
        group-closure requirement; only Bernoulli loss (irreducibly
        per-edge) stays rejected with a pointer to topology='random'."""
        from gossipfs_tpu.scenarios import (
            CorrelatedOutage,
            Flapping,
            LinkFault,
        )
        from gossipfs_tpu.scenarios.tensor import arc_match_edges, sends_mask

        n = 1024
        arc = SimConfig(n=n, topology="random_arc", fanout=16, arc_align=8,
                        remove_broadcast=False, fresh_cooldown=True)
        flap = FaultScenario(name="f", n=n, flapping=(
            Flapping(start=0, end=8, up=1, down=2,
                     nodes=tuple(range(8))),))
        require_scenario_config(arc, flap)  # accepted
        sm = np.asarray(sends_mask(compile_tensor(flap), n, jnp.int32(1)))
        assert not sm[:8].any() and sm[8:].all()
        out = FaultScenario(name="o", n=n, outages=(
            CorrelatedOutage(start=0, end=8, nodes=tuple(range(11, 19))),))
        require_scenario_config(arc, out)  # accepted since round 14
        tsc = compile_tensor(out)
        # sender half: outage members' datagrams all mute...
        sm = np.asarray(sends_mask(tsc, n, jnp.int32(3)))
        assert not sm[11:19].any() and sm[:11].all() and sm[19:].all()
        # ...receiver half: their in-edges all drop (zero match mask),
        # everyone else keeps the full window
        bases = jnp.zeros((n,), jnp.int32)
        am = np.asarray(arc_match_edges(tsc, bases, jnp.int32(3), 16, 8))
        full = (1 << (16 // 8)) - 1
        assert (am[11:19, 1] == 0).all()
        assert (am[:11, 1] == full).all() and (am[19:, 1] == full).all()
        # ...and outside the window nobody is muted
        am2 = np.asarray(arc_match_edges(tsc, bases, jnp.int32(9), 16, 8))
        assert (am2[:, 1] == full).all()
        assert np.asarray(sends_mask(tsc, n, jnp.int32(9))).all()
        loss = FaultScenario(name="l", n=n, link_faults=(
            LinkFault(start=0, end=8, rate=0.5, src=tuple(range(8)),
                      dst=tuple(range(n))),))
        with pytest.raises(ValueError, match="loss"):
            require_scenario_config(arc, loss)

    def test_cosim_reachability_confined_by_outage(self):
        """The control plane's scp/RPC reachability excludes outage
        members and dark-phase flappers for the window (cosim.
        _reachable) — a put cannot silently ack onto a blacked-out
        rack."""
        from gossipfs_tpu.cosim import CoSim
        from gossipfs_tpu.scenarios import CorrelatedOutage

        n = 12
        sim = CoSim(gossip_only_cfg(n), seed=0)
        sim.tick(2)
        sc = FaultScenario(name="rack", n=n, outages=(
            CorrelatedOutage(start=1, end=5, nodes=(6, 7, 8)),))
        sim.load_scenario(sc)
        sim.tick(2)  # inside the window
        reach = sim._reachable()
        assert reach.isdisjoint({6, 7, 8})
        assert sim.cluster.master_node in reach
        sim.tick(4)  # past the window
        assert {6, 7, 8} <= sim._reachable()


# ---------------------------------------------------------------------------
# tensor engine (the fast-lane tier-1 smoke)
# ---------------------------------------------------------------------------


class TestTensorEngine:
    def test_partition_blocks_cross_gossip_and_heals(self):
        from gossipfs_tpu.core.rounds import run_rounds

        n = 128
        cfg = gossip_only_cfg(n)
        sc = split_halves(n, start=3, end=40)
        tsc = compile_tensor(sc)
        pid = sc.partitions[0].pid(n)
        cross = pid[:, None] != pid[None, :]

        final, mcarry, _ = run_rounds(
            init_state(cfg), cfg, 30, jax.random.PRNGKey(0), scenario=tsc
        )
        status = np.asarray(final.status)
        hb = np.asarray(final.hb)
        # split accepted: no live observer still lists a cross member
        assert ((status == 1) & cross).sum() == 0
        # ZERO cross-partition heartbeat propagation: no cross copy ever
        # exceeds what had crossed by the split round (diag bumped to 3)
        assert hb[cross].max() <= 3
        assert hb[~cross].max() == 30  # same-side gossip kept flowing
        # every node was "detected" by the far side within ~t_fail of the
        # split — both sides keep detecting, partition-locally
        fd = np.asarray(mcarry.first_detect)
        assert (fd >= 3).all() and (fd <= 3 + cfg.t_fail + 4).all()

        # same scenario, horizon past heal: views fully reconverge by
        # gossip alone (t_fail + diameter + slack after heal at 40)
        final2, _, _ = run_rounds(
            init_state(cfg), cfg, 55, jax.random.PRNGKey(0), scenario=tsc
        )
        assert (np.asarray(final2.status) == 1).all()

    def test_scenario_keeps_fast_kernel_bit_equal_to_oracle(self):
        """Round 11 (fast-path unification): scenario runs keep the
        CONFIGURED merge kernel — the rr scan rewrites its sampled edges
        before the in-kernel gather — and the result is bit-equal to the
        explicitly-requested XLA oracle path (config.fallback_config).
        The old forced-substitution ValueError is gone."""
        from gossipfs_tpu.config import fallback_config
        from gossipfs_tpu.core.rounds import run_rounds

        cfg = SimConfig.packed_rr(2048, 1024, interpret=True)
        sc = split_halves(2048, start=1, end=6)
        tsc = compile_tensor(sc)
        key = jax.random.PRNGKey(0)
        out = {}
        for c in (cfg, fallback_config(cfg)):
            final, carry, per = run_rounds(
                init_state(c), c, 8, key, scenario=tsc, crash_rate=0.02,
                crash_only_events=True,
            )
            out[c.merge_kernel] = (final, carry, per)
        fr, cr, pr = out["pallas_rr_interpret"]
        fx, cx, px = out["xla"]
        assert int(fr.round) == 8
        for a, b in zip(jax.tree.leaves((fr, cr, pr)),
                        jax.tree.leaves((fx, cx, px))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_lossy_links_slow_detection_but_not_correctness(self):
        from gossipfs_tpu.bench.run import tracked_crash_events
        from gossipfs_tpu.core.rounds import run_rounds

        n = 64
        cfg = gossip_only_cfg(n)
        sc = FaultScenario(
            name="lossy", n=n,
            link_faults=(LinkFault(start=0, end=40, rate=0.4,
                                   src=tuple(range(n)),
                                   dst=tuple(range(n))),),
        )
        events, crash_rounds, churn_ok = tracked_crash_events(cfg, 25, 3, 4)
        final, mcarry, per = run_rounds(
            init_state(cfg), cfg, 25, jax.random.PRNGKey(1),
            events=events, scenario=compile_tensor(sc),
        )
        fd = np.asarray(mcarry.first_detect)
        for node, r0 in crash_rounds.items():
            # detection still lands, within t_fail plus loss-induced lag
            assert r0 + cfg.t_fail <= fd[node] <= r0 + cfg.t_fail + 8

    def test_slow_node_rule(self):
        from gossipfs_tpu.core.rounds import run_rounds

        n = 64

        def run_with(stride, t_fail):
            cfg = gossip_only_cfg(n, t_fail=t_fail,
                                  t_cooldown=max(6, t_fail + 1))
            sc = FaultScenario(
                name="slow", n=n,
                slow_nodes=(SlowNode(start=0, end=30, stride=stride,
                                     nodes=(1,)),),
            )
            _, mcarry, per = run_rounds(
                init_state(cfg), cfg, 25, jax.random.PRNGKey(2),
                scenario=compile_tensor(sc),
            )
            fp = int(np.asarray(per.false_positives).sum())
            return int(np.asarray(mcarry.first_detect)[1]), fp

        # lag well below the timeout: never detected.  (Margin matters:
        # a handicapped sender's entry ages have heavy tails under random
        # gossip — at stride 2 vs t_fail 5 the occasional age-6 streak
        # already fires, which is itself a finding only this fault class
        # surfaces.  At t_fail=10 an 11-round no-advance streak is
        # vanishingly rare.)
        fd_mild, _ = run_with(stride=2, t_fail=10)
        assert fd_mild == -1
        # lag beyond the timeout: the lagging node IS declared failed
        # while alive — a partial-failure FALSE POSITIVE, the scenario
        # class the crash-stop model could never produce
        fd_slow, fps = run_with(stride=12, t_fail=5)
        assert fd_slow >= 0 and fps > 0


# ---------------------------------------------------------------------------
# round 11 — fast-path unification: suspicion + scenarios on the rr/SWAR
# kernel, bit-equal to the XLA oracle
# ---------------------------------------------------------------------------


class TestFastPathUnification:
    """The round-11 acceptance surface: a partition + suspicion scenario
    runs on the CONFIGURED fast kernel (resident-round + SWAR) and is
    bit-equal to the explicitly-requested XLA oracle — states, carries
    (incl. first_suspect) and per-round metrics (incl. the suspicion
    counters)."""

    def test_load_scenario_runs_arc_capability_checks(self):
        """The interactive lane must reject at LOAD time what run_rounds
        rejects at call time: Bernoulli loss has no align-group form, so
        arming it on an aligned-arc detector is an error, not a silent
        no-op (the arc scenario branch only applies group-closed
        partitions + sends_mask)."""
        from gossipfs_tpu.detector.sim import SimDetector
        from gossipfs_tpu.scenarios import LinkFault

        cfg = SimConfig(n=256, topology="random_arc", fanout=8,
                        arc_align=8, remove_broadcast=False,
                        fresh_cooldown=True)
        det = SimDetector(cfg, seed=0)
        sc = FaultScenario(
            name="loss", n=256,
            link_faults=(LinkFault(start=0, end=10, rate=0.5,
                                   src=tuple(range(8)),
                                   dst=tuple(range(256))),))
        with pytest.raises(ValueError, match="no group form"):
            det.load_scenario(sc)
        assert det.scenario_status() is None  # nothing half-armed

    @pytest.mark.parametrize("topology,arc_align,fanout,elementwise", [
        # explicit-edge form: the rr scan rewrites its sampled [N, F]
        # edges before the in-kernel gather
        ("random", 1, 11, "swar"),
        # aligned-arc form: the kernel's edge_filter masked gather over
        # (base, group-match bitmask) pairs, SWAR and lanes stages
        ("random_arc", 8, 16, "swar"),
        ("random_arc", 8, 16, "lanes"),
    ])
    def test_partition_suspicion_fast_path_bit_equal_oracle(
            self, topology, arc_align, fanout, elementwise):
        import dataclasses

        from gossipfs_tpu.core.rounds import run_rounds
        from gossipfs_tpu.scenarios import Partition, SlowNode
        from gossipfs_tpu.suspicion import SuspicionParams

        n = 2048
        base = SimConfig(
            n=n, topology=topology, fanout=fanout, arc_align=arc_align,
            remove_broadcast=False, fresh_cooldown=True, t_cooldown=12,
            view_dtype="int8", hb_dtype="int8", merge_block_c=1024,
            elementwise=elementwise, t_fail=3,
            suspicion=SuspicionParams(t_suspect=2),
        )
        # a timed half/half split (sides are align-group-closed: n/2 is a
        # multiple of arc_align) riding alongside lagging senders — the
        # partition manufactures the staleness storm the SUSPECT window
        # must absorb, the slow rule drives the sender-mute path
        sc = FaultScenario(
            name="split+slow", n=n,
            partitions=(Partition(start=2, end=9,
                                  groups=(tuple(range(n // 2)),)),),
            slow_nodes=(SlowNode(start=0, end=12, stride=3,
                                 nodes=tuple(range(64))),),
        )
        tsc = compile_tensor(sc)
        key = jax.random.PRNGKey(3)
        out = {}
        for kernel in ("xla", "pallas_rr_interpret"):
            cfg = dataclasses.replace(base, merge_kernel=kernel)
            out[kernel] = run_rounds(
                init_state(cfg), cfg, 12, key, crash_rate=0.02,
                scenario=tsc, crash_only_events=True,
            )
        for a, b in zip(jax.tree.leaves(out["xla"]),
                        jax.tree.leaves(out["pallas_rr_interpret"])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # the run exercised the lifecycle, not a degenerate quiet horizon
        per = out["xla"][2]
        assert int(np.asarray(per.suspects_entered).sum()) > 0
        assert int(np.asarray(per.refutations).sum()) > 0

    def test_capacity_ladder_shape_constructs_and_is_eligible(self):
        """The acceptance shape: N=262,144 (ANCHORS_r09 ladder) with
        suspicion armed AND a partition scenario loaded constructs on
        merge_kernel='pallas_rr' / elementwise='swar' — no gating
        ValueError — is row-budget admissible per rr_shard_admissible,
        and passes the rr scan's eligibility gate (interpret stands in
        for the TPU backend check; no run here — the on-chip anchor is
        gated behind bench.py probe_rr_suspicion)."""
        import dataclasses

        from gossipfs_tpu.core.rounds import LOCAL_CTX, _rr_scan_eligible
        from gossipfs_tpu.parallel.mesh import rr_shard_admissible
        from gossipfs_tpu.suspicion import SuspicionParams

        n = 262_144
        cfg = SimConfig(
            n=n, topology="random_arc", fanout=24, arc_align=8,
            remove_broadcast=False, fresh_cooldown=True, t_cooldown=12,
            merge_kernel="pallas_rr", merge_block_c=2048, merge_block_r=512,
            view_dtype="int8", hb_dtype="int8", elementwise="swar",
            t_fail=3, suspicion=SuspicionParams(t_suspect=2),
        )
        assert cfg.merge_kernel == "pallas_rr"
        sc = split_halves(n, start=5, end=30)
        require_scenario_config(cfg, sc)
        for shards in (1, 8):
            assert rr_shard_admissible(n, shards, cfg.merge_block_c,
                                       cfg.fanout)["admissible"]
        icfg = dataclasses.replace(cfg, merge_kernel="pallas_rr_interpret")
        assert _rr_scan_eligible(icfg, n, n // 8, False, LOCAL_CTX,
                                 scenario=compile_tensor(sc))


# ---------------------------------------------------------------------------
# three-engine parity: one scenario file, same detection events
# ---------------------------------------------------------------------------


class TestEngineParity:
    def test_partition_parity_sim_vs_udp(self):
        """The same small-N partition scenario file drives the tensor sim
        and the asyncio UDP engine; both must produce the same detection
        events: each side detects exactly the other side, no same-side
        detections, and both end fully split (the satellite acceptance).
        """
        from gossipfs_tpu.detector.sim import SimDetector
        from gossipfs_tpu.detector.udp import UdpCluster

        n = 10
        side_a, side_b = set(range(5)), set(range(5, 10))
        sc = split_halves(n, start=5, end=1000)

        # -- tensor sim (ring parity mode, gossip-only dissemination)
        cfg = SimConfig(n=n, remove_broadcast=False, fresh_cooldown=True,
                        t_cooldown=6)
        det = SimDetector(cfg, seed=0)
        det.load_scenario(sc)
        det.advance(30)
        sim_events = det.drain_events()
        sim_views = {i: set(det.membership(i)) for i in range(n)}

        # -- asyncio UDP engine, same scenario object
        async def udp_run():
            c = UdpCluster(n=n, base_port=23400, period=0.05,
                           fresh_cooldown=True, scenario=sc)
            try:
                await c.start_all()
                await c.run(30)
                return (c.drain_events(),
                        {i: set(c.membership(i)) for i in c.alive_nodes()})
            finally:
                c.stop_all()

        udp_events, udp_views = asyncio.run(udp_run())

        for name, events, views in (("sim", sim_events, sim_views),
                                    ("udp", udp_events, udp_views)):
            det_by_a = {e.subject for e in events if e.observer in side_a}
            det_by_b = {e.subject for e in events if e.observer in side_b}
            assert det_by_a == side_b, (name, det_by_a)
            assert det_by_b == side_a, (name, det_by_b)
            for i, view in views.items():
                assert view == (side_a if i in side_a else side_b), (
                    name, i, view)

    def test_udp_scenario_status_and_clear(self):
        from gossipfs_tpu.detector.udp import UdpCluster

        async def run():
            c = UdpCluster(n=4, base_port=23600, period=0.05)
            try:
                await c.start_all()
                assert c.scenario_status() is None
                c.load_scenario(split_halves(4, 0, 10))
                st = c.scenario_status()
                assert st["active"] and st["name"] == "halves"
                c.clear_scenario()
                assert c.scenario_status() is None
            finally:
                c.stop_all()

        asyncio.run(run())


# ---------------------------------------------------------------------------
# CoSim under partition: SDFS quorum behavior (acceptance criterion)
# ---------------------------------------------------------------------------


class TestCoSimPartition:
    def test_minority_puts_fail_quorum_then_heal_restores(self):
        from gossipfs_tpu.cosim import CoSim
        from gossipfs_tpu.sdfs.quorum import quorum

        n = 16
        cfg = gossip_only_cfg(n)
        sim = CoSim(cfg, seed=0)
        assert sim.put("a.txt", b"v1")
        holders = list(sim.cluster.master.files["a.txt"].node_list)
        assert len(holders) == 4 and quorum(4) == 2

        # minority side: the master (node 0) plus two NON-holders — at
        # most one replica of a.txt is reachable from inside, below the
        # 2-ack quorum.  3 < min_group, so the minority also never
        # detects the far side (small groups refresh only): its view
        # stays full while its transport is cut — the harshest variant.
        others = [x for x in range(1, n) if x not in holders][:2]
        minority = tuple(sorted([0, *others]))
        sc = FaultScenario(
            name="minority", n=n,
            partitions=(Partition(start=1, end=30, groups=(minority,)),),
        )
        sim.load_scenario(sc)
        sim.tick(3)  # split active; control plane reachability confined
        assert sim.cluster.reachable == set(minority)

        # minority-side write: plan reuses the 4 holders, but <= 1 of
        # them answers from this side — the put must fail its quorum
        assert not sim.put("a.txt", b"v2-split", confirm=lambda: True)
        # reads fail their version-report quorum the same way
        assert sim.get("a.txt") is None

        # heal, let reachability recover, and write again: durability is
        # restored (all holders ack; the read returns the fresh bytes)
        sim.tick(30)
        assert sim.cluster.reachable == set(range(n))
        assert sim.put("a.txt", b"v3-healed", confirm=lambda: True)
        assert sim.get("a.txt") == b"v3-healed"


# ---------------------------------------------------------------------------
# literal-N padding (VERDICT missing #1 satellite)
# ---------------------------------------------------------------------------


class TestPadding:
    def test_padded_cohort_excludes_pads_end_to_end(self):
        """XLA-path integration at small N: pads start dead, survive
        churn AND rejoin rounds without ever entering the cohort, stay
        out of every view, and the metrics count the effective N."""
        from gossipfs_tpu.bench.run import tracked_crash_events
        from gossipfs_tpu.core.rounds import run_rounds
        from gossipfs_tpu.metrics.detection import summarize

        n_pad, n_live = 256, 250
        cfg = gossip_only_cfg(n_pad)
        events, crash_rounds, churn_ok = tracked_crash_events(
            cfg, 20, 4, 3, n_live=n_live
        )
        assert all(node < n_live for node in crash_rounds)
        assert not np.asarray(churn_ok)[n_live:].any()
        mask = jnp.arange(n_pad) < n_live
        final, mcarry, per_round = run_rounds(
            init_state(cfg, mask), cfg, 20, jax.random.PRNGKey(0),
            events=events, crash_rate=0.02, rejoin_rate=0.2,
            churn_ok=churn_ok,
        )
        alive = np.asarray(final.alive)
        status = np.asarray(final.status)
        assert not alive[n_live:].any()          # pads never resurrect
        assert (status[:, n_live:] != 1).all()   # ...or enter any view
        fd = np.asarray(mcarry.first_detect)
        assert (fd[n_live:] == -1).all()         # ...or get detected
        report = summarize(mcarry, per_round, crash_rounds,
                           n_effective=n_live)
        assert report.n == n_live
        detected = [v for v in report.ttd_first.values() if v >= 0]
        assert len(detected) == len(crash_rounds)

    def test_rr_packed_init_member_mask(self):
        """The frontier path's padded initializer: pad rows/columns start
        UNKNOWN and dead, counts reflect the live cohort only."""
        from gossipfs_tpu.core.rounds import rr_packed_init
        from gossipfs_tpu.ops import merge_pallas

        n_pad, n_live = 2048, 2000
        cfg = SimConfig.packed_rr(n_pad, 1024, interpret=True)
        mask = np.arange(n_pad) < n_live
        hb4, as4, alive, hb_base, rnd, counts = rr_packed_init(
            cfg, member_mask=mask
        )
        assert np.array_equal(np.asarray(alive), mask)
        st = np.asarray(merge_pallas.unpack_age_status(as4)[1])
        # stripe-major [nc, N, cs, LANE] -> [receiver, subject]
        st2 = st.transpose(1, 0, 2, 3).reshape(n_pad, n_pad)
        want = np.where(mask[:, None] & mask[None, :], 1, 0)
        assert np.array_equal(st2, want)
        assert np.array_equal(
            np.asarray(counts), np.where(mask, n_live, 0)
        )
        assert int(np.asarray(hb4).max()) == 0

    def test_frontier_pad_math_hits_literal_100k(self):
        from gossipfs_tpu.bench.frontier import pad_quantum

        q = pad_quantum(1024, "random_arc")
        assert q == 1024
        n_pad = -(-100_000 // q) * q
        assert n_pad == 100_352 and n_pad - 100_000 == 352
        # the padded size is an admissible rr shape at the frontier width
        from gossipfs_tpu.ops import merge_pallas

        assert merge_pallas.rr_supported(n_pad, 24, 1024, arc_align=8)


# ---------------------------------------------------------------------------
# partition metrics (metrics/detection.py)
# ---------------------------------------------------------------------------


class TestPartitionMetrics:
    def test_partition_round_stats_counts(self):
        from gossipfs_tpu.metrics.detection import partition_round_stats

        n = 4
        pid = jnp.asarray([0, 0, 1, 1], jnp.int32)
        status = jnp.asarray(
            [[1, 1, 1, 0],   # row 0 still holds cross member 2
             [1, 1, 0, 0],
             [1, 0, 1, 1],   # row 2 still holds cross member 0
             [1, 1, 1, 1]],  # row 3 is dead: ignored
            jnp.int8,
        )
        hb = jnp.zeros((n, n), jnp.int32).at[0, 2].set(7).at[3, 0].set(99)
        state = SimState(
            hb=hb, age=jnp.zeros((n, n), jnp.int8), status=status,
            alive=jnp.asarray([True, True, True, False]),
            round=jnp.int32(0), hb_base=jnp.zeros((n,), jnp.int32),
        )
        out = np.asarray(partition_round_stats(state, pid))
        cross_members, cross_hb_max, cross_complete, complete, n_alive = out
        assert cross_members == 2     # (0,2) and (2,0); dead row 3 ignored
        assert cross_hb_max == 7      # row 3's 99 is a dead observer's
        assert cross_complete == 0    # (1,2) and (2,1) missing
        assert complete == 0
        assert n_alive == 3

    def test_summarize_partition_series(self):
        from gossipfs_tpu.detector.api import DetectionEvent
        from gossipfs_tpu.metrics.detection import summarize_partition

        pid = np.asarray([0, 0, 1, 1])
        series = []
        for r in range(1, 13):
            series.append({
                "round": r,
                "cross_members": 4 if r <= 6 else 0,
                # the max is 3 at the split-boundary state (r=2) and
                # jumps INSIDE the split — exactly one counted advance
                # (a jump at r=2 itself would be pre-split gossip)
                "cross_hb_max": 5 if r >= 4 else 3,
                "cross_complete": r >= 11,
                "complete": r >= 12,
                "n_alive": 4,
            })
        events = [
            DetectionEvent(round=5, observer=0, subject=2,
                           false_positive=True),   # cross: expected
            DetectionEvent(round=6, observer=0, subject=1,
                           false_positive=True),   # same side, alive: FP
            DetectionEvent(round=7, observer=2, subject=3,
                           false_positive=False),  # tracked crash, local
        ]
        rep = summarize_partition(
            series, events, pid, split_at=2, heal_at=8,
            crash_rounds={3: 4},
        )
        assert rep.split_brain_rounds == 5      # cross_members 0 at r=7
        assert rep.view_divergence_max == 4
        assert rep.cross_hb_advances == 1       # 3 -> 5 within the split
        assert rep.reconverge_rounds == 3       # cross complete at r=11
        assert rep.full_view_rounds == 4
        assert rep.local_ttd == {3: 3}
        assert rep.cross_detections == 1
        assert rep.local_false_positives == 1
        assert rep.local_fp_rate > 0


# ---------------------------------------------------------------------------
# CLI verbs
# ---------------------------------------------------------------------------


class TestCliVerbs:
    def test_scenario_load_status_clear(self, tmp_path):
        from gossipfs_tpu.cosim import CoSim
        from gossipfs_tpu.shim import cli

        path = tmp_path / "split.json"
        path.write_text(split_halves(10, 2, 20).to_json())
        cfg = SimConfig(n=10, remove_broadcast=False, fresh_cooldown=True)
        sim = CoSim(cfg, seed=0)
        out = io.StringIO()
        assert cli.dispatch(sim, f"scenario load {path}", out=out)
        assert "armed 'halves'" in out.getvalue()
        cli.dispatch(sim, "advance 3", out=out)
        cli.dispatch(sim, "scenario status", out=out)
        assert "ACTIVE" in out.getvalue()
        cli.dispatch(sim, "scenario clear", out=out)
        out2 = io.StringIO()
        cli.dispatch(sim, "scenario status", out=out2)
        assert "no scenario armed" in out2.getvalue()

    def test_load_on_broadcast_config_reports_error(self, tmp_path):
        from gossipfs_tpu.cosim import CoSim
        from gossipfs_tpu.shim import cli

        path = tmp_path / "split.json"
        path.write_text(split_halves(10, 2, 20).to_json())
        sim = CoSim(SimConfig(n=10), seed=0)  # reference broadcast mode
        out = io.StringIO()
        assert cli.dispatch(sim, f"scenario load {path}", out=out)
        assert "error:" in out.getvalue()
        assert "remove_broadcast" in out.getvalue()

    def test_gossip_only_flag(self):
        from gossipfs_tpu.shim import cli

        args = cli.make_parser().parse_args(["--n", "8", "--gossip-only"])
        assert args.gossip_only


# ---------------------------------------------------------------------------
# deploy variant (slow lane): the same rule table over OS processes
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_deploy_partition_split_brain(tmp_path):
    """The per-process deployment under the same declarative partition:
    the launcher pushes one rule table over the control plane, each
    daemon's send hook drops cross-side datagrams, and the two sides
    converge to independent views — detection/REMOVE all crossing real
    process boundaries."""
    from gossipfs_tpu.deploy.launcher import Cluster

    n = 8
    side_a = tuple(range(4))
    # t_fail=15, not the default 5: while the split settles, each side's
    # freshness paths route past dropped cross edges — at t_fail=5 a 4/4
    # ring split sits exactly on the false-positive cascade threshold
    # (the BASELINE ring-fragility finding) and a side can collapse on a
    # loaded host.  The margin makes the test pin the PARTITION behavior,
    # not the ring's marginality.
    cluster = Cluster(n, period=0.1, root=str(tmp_path), t_fail=15)
    try:
        cluster.start(timeout=90.0)
        sc = FaultScenario(
            name="deploy-split", n=n,
            partitions=(Partition(start=0, end=100_000, groups=(side_a,)),),
        )
        acked = cluster.load_scenario(sc)
        assert set(acked) == set(range(n))
        status = cluster.scenario_status()
        assert len(status) == n and all(ln["armed"] for ln in status)

        want = {
            i: (set(side_a) if i in side_a else set(range(4, n)))
            for i in range(n)
        }
        deadline = time.monotonic() + 60.0
        views = {}
        while time.monotonic() < deadline:
            views = {i: set(cluster.client(i).lsm(i)) for i in range(n)}
            if views == want:
                break
            time.sleep(0.2)
        assert views == want, f"views never fully split: {views}"

        # each side collectively logged detections of the OTHER side only
        # (per-node sets can be empty: a node that learned of a far-side
        # member via a peer's REMOVE broadcast never fires its own
        # detector — reference dissemination semantics)
        for side in (set(side_a), set(range(4, n))):
            subjects: set[int] = set()
            for i in side:
                lines = cluster.client(i).call(
                    "Grep", pattern="detected failure"
                ).get("lines") or []
                subjects |= {int(ln["subject"]) for ln in lines}
            assert subjects and subjects <= (set(range(n)) - side), (
                side, subjects)
    finally:
        cluster.stop()
