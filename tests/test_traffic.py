"""Traffic-plane coverage (gossipfs_tpu/traffic/): open-loop workload,
tensorized placement/repair planning, the durability harness, and the
quorum single-ownership lint.

Fast lane throughout (tier-1): the put/get/churn smoke asserting no
acked-write loss is the subsystem's standing acceptance check, and the
quorum lint fails any module that re-derives the W=3/R=2 arithmetic
instead of importing ``sdfs/quorum.py``.
"""

from __future__ import annotations

import io
import json
import pathlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gossipfs_tpu.sdfs import placement
from gossipfs_tpu.sdfs.cluster import SDFSCluster
from gossipfs_tpu.sdfs.master import BATCH_PLAN_THRESHOLD, SDFSMaster
from gossipfs_tpu.sdfs.quorum import (
    claimed_write_quorum,
    read_quorum,
    write_quorum,
)
from gossipfs_tpu.sdfs.types import REPLICATION_FACTOR
from gossipfs_tpu.traffic import audit
from gossipfs_tpu.traffic.planner import (
    ReplicaTable,
    commit_repairs,
    plan_repairs_tensor,
)
from gossipfs_tpu.traffic.workload import Workload, WorkloadSpec

pytestmark = pytest.mark.traffic

REPO = pathlib.Path(__file__).resolve().parent.parent


# ---------------------------------------------------------------------------
# quorum arithmetic: single-owned, imported everywhere
# ---------------------------------------------------------------------------


class TestQuorumSingleOwner:
    def test_named_constants(self):
        # the DEPLOYED arithmetic (slave.go:717-722 integer division):
        # W = R = floor((n+1)/2) = 2-of-4; the report CLAIMS W=3/R=2
        assert write_quorum(4) == 2
        assert read_quorum(4) == 2
        assert claimed_write_quorum(4) == 3
        # the claimed pair satisfies the intersection inequality W + R > n;
        # the deployed pair does NOT (the documented discrepancy)
        assert claimed_write_quorum(4) + read_quorum(4) > 4
        assert write_quorum(4) + read_quorum(4) == 4

    def test_no_rederived_quorum_outside_owner(self):
        # Round 15: the old regex grep (traffic/, sdfs/ and two benches
        # only) migrated onto the gossipfs-lint registry — the AST rule
        # covers the idiomatic int forms (x + 1) // 2 and x // 2 + 1
        # across the WHOLE tree (gossipfs_tpu/ + tools/), and its
        # trigger fixture lives in tests/fixtures/lint/.  This wrapper
        # keeps the enforcement at its historical home on the fast lane.
        from gossipfs_tpu.analysis import REGISTRY, RepoIndex

        findings = REGISTRY["quorum-ownership"].check(RepoIndex())
        assert not findings, (
            "quorum arithmetic re-derived outside sdfs/quorum.py:\n"
            + "\n".join(str(f) for f in findings)
        )

    def test_planner_imports_the_owner(self):
        src = (REPO / "gossipfs_tpu" / "traffic" / "planner.py").read_text()
        assert "from gossipfs_tpu.sdfs.quorum import" in src
        assert "read_quorum" in src and "write_quorum" in src


# ---------------------------------------------------------------------------
# place_batch statistical uniformity at N=100k
# ---------------------------------------------------------------------------


def _chi_square(counts: np.ndarray, total: int) -> float:
    exp = total / len(counts)
    return float(((counts - exp) ** 2 / exp).sum())


class TestPlaceBatchUniformity:
    N = 100_000
    ALIVE = 256       # scattered alive subset inside the 100k mask
    FILES = 4096

    def _mask(self) -> tuple[jnp.ndarray, np.ndarray]:
        # alive ids spread across the whole index range, INCLUDING the
        # very last index (the reference's rand.Intn(len-1) can never
        # pick the last member — master.go:129-150; our uniform draw must)
        ids = np.linspace(0, self.N - 1, self.ALIVE).round().astype(int)
        ids[-1] = self.N - 1
        mask = np.zeros(self.N, dtype=bool)
        mask[ids] = True
        return jnp.asarray(mask), ids

    def test_sampled_uniform_at_100k(self):
        mask, ids = self._mask()
        rows = np.asarray(placement.place_batch(
            jax.random.PRNGKey(0), mask, self.FILES, method="sampled"
        ))
        # every row fully placed with distinct alive nodes
        assert (rows >= 0).all()
        assert all(len(set(r)) == REPLICATION_FACTOR for r in rows)
        alive_set = set(ids.tolist())
        picked = rows.ravel()
        assert set(picked.tolist()) <= alive_set
        # uniformity: chi-square over the alive cohort.  dof = 255, mean
        # 255, std ~22.6 — 400 is a ~6-sigma acceptance bound (seeded
        # draw, deterministic)
        counts = np.bincount(picked, minlength=self.N)[ids]
        total = self.FILES * REPLICATION_FACTOR
        assert _chi_square(counts, total) < 400.0
        # the Intn(len-1) deviation: the LAST member is placeable
        assert counts[-1] > 0
        assert (counts > 0).all()

    def test_auto_dispatch_picks_sampled_past_gumbel_ceiling(self):
        mask, _ = self._mask()
        key = jax.random.PRNGKey(1)
        auto = placement.place_batch(key, mask, 8, method="auto")
        sampled = placement.place_batch(key, mask, 8, method="sampled")
        assert (np.asarray(auto) == np.asarray(sampled)).all()
        assert self.N > placement.BATCH_GUMBEL_MAX_N

    def test_gumbel_uniform_and_last_member(self):
        # the exact path at control-plane scale, same acceptance shape
        n, files = 256, 4096
        mask = jnp.ones(n, dtype=bool)
        rows = np.asarray(placement.place_batch(
            jax.random.PRNGKey(2), mask, files, method="gumbel"
        ))
        counts = np.bincount(rows.ravel(), minlength=n)
        assert _chi_square(counts, files * REPLICATION_FACTOR) < 400.0
        assert counts[n - 1] > 0

    def test_place_batch_np_uniform_and_last_member(self):
        # the metadata master's host-side batch path
        # (SDFSMaster.handle_put_batch)
        members = np.arange(100, 100 + 256)
        rng = np.random.default_rng(3)
        rows = placement.place_batch_np(rng, members, 4096)
        assert all(len(set(r.tolist())) == REPLICATION_FACTOR for r in rows)
        counts = np.bincount(rows.ravel() - 100, minlength=256)
        assert _chi_square(counts, 4096 * REPLICATION_FACTOR) < 400.0
        assert counts[-1] > 0  # the last member is placeable

    def test_short_mask_pads_with_minus_one(self):
        mask = jnp.zeros(64, dtype=bool).at[jnp.array([3, 9])].set(True)
        rows = np.asarray(placement.place_batch(
            jax.random.PRNGKey(4), mask, 16, method="sampled"
        ))
        # only 2 alive: exactly two real picks per row, rest -1
        assert ((rows >= 0).sum(axis=1) == 2).all()
        assert set(rows[rows >= 0].tolist()) == {3, 9}


# ---------------------------------------------------------------------------
# tensorized repair planning: determinism, budget, python-planner parity
# ---------------------------------------------------------------------------


def _table(n=512, files=96, seed=0):
    alive = jnp.ones(n, dtype=bool)
    t = ReplicaTable(files + 8, n, seed=seed)
    t.place(alive, files)
    return t


class TestPlanRepairsTensor:
    def test_deterministic_under_fixed_key(self):
        t = _table()
        alive = np.ones(t.n, dtype=bool)
        alive[10:200] = False  # mass failure
        a = jnp.asarray(alive)
        key = jax.random.PRNGKey(7)
        p1 = plan_repairs_tensor(key, t.replicas, jnp.int32(t.n_files),
                                 a, a, 32)
        p2 = plan_repairs_tensor(key, t.replicas, jnp.int32(t.n_files),
                                 a, a, 32)
        for x, y in zip(p1, p2):
            assert (np.asarray(x) == np.asarray(y)).all()

    def test_budget_caps_executions_and_most_deficient_first(self):
        t = _table()
        alive = np.ones(t.n, dtype=bool)
        alive[0:300] = False
        a = jnp.asarray(alive)
        budget = 8
        plan = plan_repairs_tensor(jax.random.PRNGKey(1), t.replicas,
                                   jnp.int32(t.n_files), a, a, budget)
        n_valid = int(np.asarray(plan.valid).sum())
        assert n_valid <= budget
        deficient = int(plan.deficient)
        assert deficient >= n_valid
        # most-deficient-first: the chosen needs are the maximal needs
        # across the whole deficient set (top_k on the deficiency score)
        replicas = np.asarray(t.replicas)[: t.n_files]
        w = ((replicas >= 0) & alive[np.clip(replicas, 0, None)]).sum(axis=1)
        cand = w[(w > 0) & (w < REPLICATION_FACTOR)]
        worst = np.sort(REPLICATION_FACTOR - cand)[::-1][:n_valid]
        chosen = np.sort(np.asarray(plan.need)[np.asarray(plan.valid)])[::-1]
        assert (chosen == worst).all()

    def test_parity_with_python_planner_deficiency_set(self):
        # same replica table handed to both planners: identical deficient
        # file sets and identical per-file need counts (sources/picks are
        # independent uniform draws — decisions, not byte choices, match)
        n, files = 96, 40
        t = _table(n=n, files=files, seed=3)
        alive = np.ones(n, dtype=bool)
        alive[5:40] = False
        a = jnp.asarray(alive)
        plan = plan_repairs_tensor(jax.random.PRNGKey(2), t.replicas,
                                   jnp.int32(t.n_files), a, a, files)

        m = SDFSMaster(seed=3)
        live = [i for i in range(n) if alive[i]]
        m.update_member(live)
        replicas = np.asarray(t.replicas)[:files]
        from gossipfs_tpu.sdfs.types import FileInfo

        for i, row in enumerate(replicas):
            m.files[f"f{i}"] = FileInfo(node_list=[int(x) for x in row],
                                        version=1, timestamp=0)
        plans_py = m.plan_repairs(live)
        need_py = {int(p.file[1:]): len(p.new_nodes) for p in plans_py}

        valid = np.asarray(plan.valid)
        idx = np.asarray(plan.idx)[valid]
        need_tensor = dict(zip(idx.tolist(),
                               np.asarray(plan.need)[valid].tolist()))
        assert need_tensor == need_py

    def test_commit_repairs_keeps_survivors_and_lands_picks(self):
        t = _table(n=64, files=8, seed=5)
        alive = np.ones(64, dtype=bool)
        alive[0:40] = False
        a = jnp.asarray(alive)
        plan = plan_repairs_tensor(jax.random.PRNGKey(3), t.replicas,
                                   jnp.int32(t.n_files), a, a, 8)
        before = np.asarray(t.replicas)
        after = np.asarray(commit_repairs(t.replicas, plan.idx, plan.valid,
                                          plan.picks, a))
        valid = np.asarray(plan.valid)
        for row_i, ok in zip(np.asarray(plan.idx), valid):
            old = set(before[row_i][before[row_i] >= 0].tolist())
            new = after[row_i][after[row_i] >= 0]
            if not ok:
                assert set(new.tolist()) == old
                continue
            survivors = {x for x in old if alive[x]}
            assert survivors <= set(new.tolist())       # survivors kept
            assert len(set(new.tolist())) == len(new)   # distinct
            for x in set(new.tolist()) - old:
                assert alive[x]                         # picks are alive

    def test_replica_table_storm_drains_at_budget(self):
        t = _table(n=256, files=64, seed=9)
        alive = np.ones(256, dtype=bool)
        alive[64:128] = False  # rack kill
        a = jnp.asarray(alive)
        budget = 6
        passes, drained = 0, False
        while passes < 64:
            out = t.plan_and_commit(a, a, budget)
            assert out["repairs_executed"] <= budget
            passes += 1
            if out["repairs_pending"] == 0 and out["repairs_executed"] == 0:
                drained = True
                break
        assert drained
        stats = t.stats(a, a)
        # full recovery: every file back at k live replicas
        assert stats["replica_histogram"][REPLICATION_FACTOR] == t.n_files
        assert stats["write_quorum_reachable"] == t.n_files


# ---------------------------------------------------------------------------
# open-loop workload: determinism, rate accounting, mix
# ---------------------------------------------------------------------------


class TestWorkload:
    def test_ops_are_pure_per_round(self):
        spec = WorkloadSpec(rate=5.5, n_keys=32, seed=4)
        a, b = Workload(spec), Workload(spec)
        for r in (0, 3, 17):
            assert a.ops(r) == b.ops(r) == a.ops(r)

    def test_open_loop_rate_accumulates(self):
        wl = Workload(WorkloadSpec(rate=2.75, n_keys=8))
        horizon = 40
        total = sum(wl.arrivals(r) for r in range(horizon))
        assert total == int(2.75 * horizon)

    def test_mix_fractions(self):
        wl = Workload(WorkloadSpec(rate=64.0, put_frac=0.5,
                                   delete_frac=0.1, n_keys=64, seed=1))
        ops = [op for r in range(32) for op in wl.ops(r)]
        frac = {k: sum(op.kind == k for op in ops) / len(ops)
                for k in ("put", "get", "delete")}
        assert abs(frac["put"] - 0.5) < 0.05
        assert abs(frac["delete"] - 0.1) < 0.03
        assert abs(frac["get"] - 0.4) < 0.05

    def test_zipf_skews_and_uniform_does_not(self):
        def key_counts(pop):
            wl = Workload(WorkloadSpec(rate=64.0, n_keys=64, seed=2,
                                       popularity=pop, zipf_s=1.2))
            counts: dict[str, int] = {}
            for r in range(32):
                for op in wl.ops(r):
                    counts[op.key] = counts.get(op.key, 0) + 1
            return sorted(counts.values(), reverse=True)

        zipf, uni = key_counts("zipf"), key_counts("uniform")
        # zipf: the hottest key dominates; uniform: it doesn't
        assert zipf[0] > 4 * (sum(zipf) / len(zipf))
        assert uni[0] < 2.5 * (sum(uni) / len(uni))

    def test_payload_cap_and_digest_determinism(self):
        spec = WorkloadSpec(rate=1.0, payload_cap=4096)
        wl = Workload(spec)
        data = wl.payload("f1.txt", 7, 1_048_576)
        assert len(data) == 4096  # logical size rides the op, bytes capped
        assert data == Workload(spec).payload("f1.txt", 7, 1_048_576)

    def test_spec_validation(self):
        with pytest.raises(ValueError):
            WorkloadSpec(put_frac=0.9, delete_frac=0.3)
        with pytest.raises(ValueError):
            WorkloadSpec(popularity="hot")
        with pytest.raises(ValueError):
            WorkloadSpec(rate=0.0)


# ---------------------------------------------------------------------------
# batch put path
# ---------------------------------------------------------------------------


class TestPutBatch:
    def test_batch_acks_and_places_distinctly(self):
        c = SDFSCluster(16, seed=1)
        items = [(f"b{i}.txt", b"x" * 64)
                 for i in range(BATCH_PLAN_THRESHOLD + 8)]
        results = c.put_batch(items, now=0)
        assert all(results.values())
        for name, _ in items:
            info = c.master.files[name]
            assert len(set(info.node_list)) == REPLICATION_FACTOR
            assert info.version == 1

    def test_batch_respects_conflict_window(self):
        c = SDFSCluster(8, seed=1)
        assert c.put("a.txt", b"v1", now=0)
        res = c.put_batch([("a.txt", b"v2"), ("new.txt", b"n")], now=10)
        assert res["a.txt"] is False        # unconfirmed conflict rejected
        assert res["new.txt"] is True
        res = c.put_batch([("a.txt", b"v2")], now=11, confirm=lambda: True)
        assert res["a.txt"] is True
        assert c.master.files["a.txt"].version == 2

    def test_batch_matches_sequential_semantics(self):
        # small batch (below threshold): byte-for-byte the sequential path
        c1, c2 = SDFSCluster(12, seed=7), SDFSCluster(12, seed=7)
        items = [(f"s{i}.txt", bytes([i]) * 32) for i in range(4)]
        res_batch = c1.put_batch(items, now=5)
        res_seq = {nm: c2.put(nm, data, now=5) for nm, data in items}
        assert res_batch == res_seq
        for nm, _ in items:
            assert (c1.master.files[nm].node_list
                    == c2.master.files[nm].node_list)


# ---------------------------------------------------------------------------
# repair budget at the cluster/cosim seam
# ---------------------------------------------------------------------------


class TestRepairBudget:
    def test_fail_recover_budget_defers_and_drains(self):
        c = SDFSCluster(16, seed=2)
        for i in range(10):
            assert c.put(f"f{i}.txt", b"data" * 16, now=0)
        victims = {1, 2, 3, 4}
        c.update_membership([x for x in range(16) if x not in victims])
        total_deficient = len(c.master.plan_repairs(c.live,
                                                    reachable=c.reachable))
        assert total_deficient > 3
        done = c.fail_recover(budget=3)
        assert len(done) == 3
        assert c.last_repair_pending == total_deficient - 3
        # subsequent passes drain the backlog to zero
        rounds = 0
        while c.last_repair_pending and rounds < 16:
            c.fail_recover(budget=3)
            rounds += 1
        assert c.last_repair_pending == 0
        assert not c.master.plan_repairs(c.live, reachable=c.reachable)

    def test_zero_budget_rejected(self):
        # budget=0 would defer every plan forever while the co-sim
        # reschedules a full planning sweep each round: fail fast at both
        # owners (construction and the recovery pass itself)
        c = SDFSCluster(8, seed=0)
        with pytest.raises(ValueError):
            c.fail_recover(budget=0)
        from gossipfs_tpu.config import SimConfig
        from gossipfs_tpu.cosim import CoSim

        with pytest.raises(ValueError):
            CoSim(SimConfig(n=8, topology="ring", fanout=3), repair_budget=0)

    def test_budget_executes_most_deficient_first(self):
        c = SDFSCluster(16, seed=4)
        assert c.put("deep.txt", b"d" * 16, now=0)
        assert c.put("shallow.txt", b"s" * 16, now=0)
        # pin the replica sets (metadata + bytes) so the deficiency gap is
        # exact: after killing {1,2,3,5}, deep keeps 1 survivor and
        # shallow keeps 3 — the budget=1 pass must spend on deep
        for name, nodes, data in (("deep.txt", [1, 2, 3, 4], b"d" * 16),
                                  ("shallow.txt", [4, 5, 6, 7], b"s" * 16)):
            info = c.master.files[name]
            for nd in nodes:
                c.stores[nd].put(name, data, info.version)
            info.node_list = nodes
        c.update_membership([x for x in range(16) if x not in {1, 2, 3, 5}])
        done = c.fail_recover(budget=1)
        assert [p.file for p in done] == ["deep.txt"]
        assert c.last_repair_pending == 1  # shallow deferred, not dropped


# ---------------------------------------------------------------------------
# the tier-1 smoke: small-N put/get/churn, no acked write lost
# ---------------------------------------------------------------------------


class TestTrafficSmoke:
    def test_steady_state_no_loss(self):
        from gossipfs_tpu.traffic.harness import steady_state

        out = steady_state(12, 6, WorkloadSpec(rate=4.0, n_keys=16,
                                               put_frac=0.5), seed=0)
        assert out["ops_acked"] > 0
        assert out["durability"]["harness"]["lost"] == 0
        assert out["durability"]["events"]["lost"] == 0
        assert out["durability"]["match"]
        assert out["traffic_vitals"]["ops_issued"] == out["ops_issued"]

    def test_churn_no_acked_write_lost(self):
        from gossipfs_tpu.traffic.harness import churn

        out = churn(16, 10, WorkloadSpec(rate=4.0, n_keys=16, put_frac=0.5),
                    crashes=2, seed=1)
        assert out["crashed"]
        assert out["durability"]["harness"]["files_acked"] > 0
        assert out["durability"]["harness"]["lost"] == 0
        assert out["durability"]["events"]["lost"] == 0
        assert out["durability"]["match"]
        # crashes actually took replicas with them: repair happened
        assert out["durability"]["harness"]["repair_events"] >= 0


# ---------------------------------------------------------------------------
# event-replay audit + timeline attachment
# ---------------------------------------------------------------------------


class TestAudit:
    def test_event_replay_counts_loss(self):
        from gossipfs_tpu.obs.schema import Event

        evs = [
            Event(round=1, observer=0, subject=-1, kind="replica_put",
                  detail={"file": "a", "version": 1, "replicas": [1, 2]}),
            Event(round=2, observer=-1, subject=1, kind="crash"),
            Event(round=3, observer=1, subject=-1, kind="replica_repair",
                  detail={"file": "a", "version": 1, "targets": [3]}),
            Event(round=4, observer=-1, subject=2, kind="crash"),
            Event(round=4, observer=-1, subject=3, kind="crash"),
        ]
        out = audit.durability_from_events(evs)
        assert out["acked_writes"] == 1 and out["repair_events"] == 1
        assert out["lost"] == 1 and out["lost_files"] == ["a"]
        assert out["repair_complete_round"] == 3
        # a surviving holder flips the verdict
        evs.append(Event(round=5, observer=-1, subject=3, kind="join"))
        assert audit.durability_from_events(evs)["lost"] == 0
        # a delete retires the obligation entirely
        evs.append(Event(round=6, observer=0, subject=-1,
                         kind="replica_delete", detail={"file": "a"}))
        out = audit.durability_from_events(evs)
        assert out["files_acked"] == 0 and out["lost"] == 0

    def test_timeline_attaches_durability_to_traffic_stream(self, tmp_path):
        from gossipfs_tpu.traffic.harness import steady_state

        trace = tmp_path / "steady.jsonl"
        out = steady_state(12, 5, WorkloadSpec(rate=4.0, n_keys=12,
                                               put_frac=0.6), seed=2,
                           trace=str(trace))
        import subprocess
        import sys

        proc = subprocess.run(
            [sys.executable, str(REPO / "tools" / "timeline.py"),
             str(trace), "--json"],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stderr[-500:]
        doc = json.loads(proc.stdout.strip().splitlines()[-1])
        # the analyzer re-derived the SAME durability facts from the
        # stream alone
        assert doc["durability"]["lost"] == 0
        assert (doc["durability"]["acked_writes"]
                == out["durability"]["events"]["acked_writes"])
        assert doc["client_ops"]["issued"] == out["ops_issued"]
        assert doc["client_ops"]["acked"] == out["ops_acked"]


# ---------------------------------------------------------------------------
# surfaces: CLI verb + sdfs_ops --trace
# ---------------------------------------------------------------------------


class TestSurfaces:
    def test_cli_traffic_status_verb(self):
        from gossipfs_tpu.config import SimConfig
        from gossipfs_tpu.cosim import CoSim
        from gossipfs_tpu.shim.cli import dispatch

        sim = CoSim(SimConfig(n=8, topology="ring", fanout=3))
        sim.put("t.txt", b"bytes")
        sim.get("t.txt")
        out = io.StringIO()
        assert dispatch(sim, "traffic status", out=out)
        line = out.getvalue()
        assert "ops issued=2 acked=2" in line
        assert "repairs pending=0 done=0" in line
        out = io.StringIO()
        dispatch(sim, "traffic bogus", out=out)
        assert "unknown traffic verb" in out.getvalue()

    def test_cli_metrics_includes_traffic_vitals(self):
        from gossipfs_tpu.config import SimConfig
        from gossipfs_tpu.cosim import CoSim
        from gossipfs_tpu.shim.cli import dispatch

        sim = CoSim(SimConfig(n=8, topology="ring", fanout=3))
        sim.put("t.txt", b"bytes")
        out = io.StringIO()
        dispatch(sim, "metrics", out=out)
        assert "ops_issued=1" in out.getvalue()

    def test_drive_shim_matches_cosim_counts(self):
        # the SAME op stream through the gRPC process boundary: issued
        # counts identical to the in-process driver, everything acked on
        # a healthy cohort
        from gossipfs_tpu.config import SimConfig
        from gossipfs_tpu.cosim import CoSim
        from gossipfs_tpu.shim.client import ShimClient
        from gossipfs_tpu.shim.service import ShimServer
        from gossipfs_tpu.traffic.workload import drive_cosim, drive_shim

        spec = WorkloadSpec(rate=3.0, n_keys=8, put_frac=0.8,
                            delete_frac=0.0, seed=6)
        rounds = 4

        sim_a = CoSim(SimConfig(n=12), seed=3)
        sim_a.tick(3)
        local = drive_cosim(sim_a, Workload(spec), rounds)

        sim_b = CoSim(SimConfig(n=12), seed=3)
        server = ShimServer(sim_b, port=0).start()
        client = ShimClient(server.address, timeout=10.0)
        try:
            client.advance(3)
            remote = drive_shim(client, Workload(spec), rounds,
                                start_round=sim_b.round)
        finally:
            client.close()
            server.stop()
        assert remote["ops_issued"] == local["ops_issued"]
        assert remote["ops_acked"] == local["ops_acked"]
        for kind in ("put", "get", "delete"):
            assert (remote["by_op"][kind]["issued"]
                    == local["by_op"][kind]["issued"])

    def test_sdfs_ops_trace_stream(self, tmp_path):
        from gossipfs_tpu.bench import sdfs_ops
        from gossipfs_tpu.obs import schema

        trace = tmp_path / "ops.jsonl"
        doc = sdfs_ops.run(sizes=(1024,), reps=1, trace=str(trace))
        assert doc["rows"]
        lines = trace.read_text().strip().splitlines()
        header = json.loads(lines[0])
        assert schema.is_header(header)          # self-describing
        assert header["source"] == "sdfs_ops"
        rows = [json.loads(ln) for ln in lines[1:]]
        assert all(r["kind"] == "client_op" for r in rows)
        # 1 size x 2 cluster sizes x (1 warmup + 1 rep) x 3 ops
        assert len(rows) == 12
        assert {r["detail"]["op"] for r in rows} == {"insert", "update",
                                                     "read"}
