"""Multi-chip sharding: the identical kernel over an 8-device virtual mesh.

Column (subject-axis) sharding must be a pure performance transform — final
state and metrics bit-identical to the single-device run (GSPMD partitions the
same program).  This is the stand-in for a v5e-8 (conftest forces 8 virtual
CPU devices).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.core.rounds import run_rounds
from gossipfs_tpu.core.state import RoundEvents, init_state
from gossipfs_tpu.parallel.mesh import AXIS, make_mesh, shard_state, state_shardings
from gossipfs_tpu.sdfs.placement import place_batch

KEY = jax.random.PRNGKey(42)


def crash_events(num_rounds, n, round_, nodes):
    crash = np.zeros((num_rounds, n), dtype=bool)
    crash[round_, nodes] = True
    z = jnp.zeros((num_rounds, n), dtype=bool)
    return RoundEvents(crash=jnp.asarray(crash), leave=z, join=z)


class TestShardedEquivalence:
    def test_eight_devices_available(self):
        assert len(jax.devices()) == 8

    @pytest.mark.parametrize("topology,fanout", [("ring", 3), ("random", 6)])
    def test_sharded_run_matches_single_device(self, topology, fanout):
        cfg = SimConfig(n=64, topology=topology, fanout=fanout)
        ev = crash_events(25, cfg.n, 8, [11, 30])

        base = run_rounds(init_state(cfg), cfg, 25, KEY, events=ev)

        mesh = make_mesh()
        sharded_state = shard_state(init_state(cfg), mesh)
        got = run_rounds(sharded_state, cfg, 25, KEY, events=ev)

        for a, b in zip(jax.tree.leaves(base), jax.tree.leaves(got)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_state_stays_column_sharded(self):
        cfg = SimConfig(n=64, topology="random", fanout=6)
        mesh = make_mesh()
        st = shard_state(init_state(cfg), mesh)
        final, _, _ = run_rounds(st, cfg, 10, KEY)
        spec = final.hb.sharding.spec
        assert tuple(spec) == (None, AXIS)

    def test_shardings_pytree_matches_state(self):
        cfg = SimConfig(n=16)
        mesh = make_mesh()
        sh = state_shardings(mesh)
        st = init_state(cfg)
        jax.tree.map(lambda *_: None, st, sh)  # same structure or raises


class TestPlacementBatch:
    def test_distinct_live_replicas(self):
        alive = jnp.ones((32,), dtype=bool).at[jnp.array([3, 4, 5])].set(False)
        out = np.asarray(place_batch(KEY, alive, n_files=50))
        assert out.shape == (50, 4)
        for row in out:
            assert len(set(row.tolist())) == 4
            assert not (set(row.tolist()) & {3, 4, 5})

    def test_underfull_cluster_pads_with_minus_one(self):
        alive = jnp.zeros((8,), dtype=bool).at[jnp.array([1, 2])].set(True)
        out = np.asarray(place_batch(KEY, alive, n_files=3))
        assert (out[:, :2] >= 0).all()
        assert (out[:, 2:] == -1).all()

    def test_roughly_uniform(self):
        alive = jnp.ones((16,), dtype=bool)
        out = np.asarray(place_batch(KEY, alive, n_files=2000))
        counts = np.bincount(out.ravel(), minlength=16)
        expected = 2000 * 4 / 16
        assert (np.abs(counts - expected) < expected * 0.25).all()
