"""Multi-chip sharding: the identical kernel over an 8-device virtual mesh.

Column (subject-axis) sharding must be a pure performance transform — final
state and metrics bit-identical to the single-device run (GSPMD partitions the
same program).  This is the stand-in for a v5e-8 (conftest forces 8 virtual
CPU devices).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.core.rounds import run_rounds
from gossipfs_tpu.core.state import RoundEvents, init_state
from gossipfs_tpu.parallel.mesh import AXIS, make_mesh, shard_state, state_shardings
from gossipfs_tpu.sdfs.placement import place_batch

KEY = jax.random.PRNGKey(42)


def crash_events(num_rounds, n, round_, nodes):
    crash = np.zeros((num_rounds, n), dtype=bool)
    crash[round_, nodes] = True
    z = jnp.zeros((num_rounds, n), dtype=bool)
    return RoundEvents(crash=jnp.asarray(crash), leave=z, join=z)


class TestShardedEquivalence:
    def test_eight_devices_available(self):
        assert len(jax.devices()) == 8

    @pytest.mark.parametrize("topology,fanout", [("ring", 3), ("random", 6)])
    def test_sharded_run_matches_single_device(self, topology, fanout):
        cfg = SimConfig(n=64, topology=topology, fanout=fanout)
        ev = crash_events(25, cfg.n, 8, [11, 30])

        base = run_rounds(init_state(cfg), cfg, 25, KEY, events=ev)

        mesh = make_mesh()
        sharded_state = shard_state(init_state(cfg), mesh)
        got = run_rounds(sharded_state, cfg, 25, KEY, events=ev)

        for a, b in zip(jax.tree.leaves(base), jax.tree.leaves(got)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_state_stays_column_sharded(self):
        cfg = SimConfig(n=64, topology="random", fanout=6)
        mesh = make_mesh()
        st = shard_state(init_state(cfg), mesh)
        final, _, _ = run_rounds(st, cfg, 10, KEY)
        spec = final.hb.sharding.spec
        assert tuple(spec) == (None, AXIS)

    def test_shardings_pytree_matches_state(self):
        cfg = SimConfig(n=16)
        mesh = make_mesh()
        sh = state_shardings(mesh)
        st = init_state(cfg)
        jax.tree.map(lambda *_: None, st, sh)  # same structure or raises


class TestShardMapRunner:
    """run_rounds_sharded: the explicit shard_map path the pallas kernel
    needs on a real multi-chip mesh (GSPMD would all-gather around the
    custom call).  Must be bit-identical to the single-device run."""

    @pytest.mark.parametrize(
        "kernel,hb_dtype",
        [("xla", "int32"),
         # interpreter-mode pallas shards are deep but slow; the xla param
         # pins the sharded arithmetic in the fast lane
         pytest.param("pallas_interpret", "int32", marks=pytest.mark.slow),
         pytest.param("pallas_interpret", "int16", marks=pytest.mark.slow)],
    )
    def test_matches_single_device(self, kernel, hb_dtype):
        """Includes the int16 storage mode: hb_base is a subject-sharded
        [N] vector, so the per-shard rebase arithmetic must line up with
        the shard's column offset."""
        from gossipfs_tpu.core.state import RoundEvents
        from gossipfs_tpu.parallel.mesh import run_rounds_sharded

        cfg = SimConfig(n=1024, topology="random", fanout=8,
                        merge_kernel=kernel, hb_dtype=hb_dtype)
        crash = np.zeros((30, cfg.n), dtype=bool)
        crash[5, [7, 300]] = True
        join = np.zeros((30, cfg.n), dtype=bool)
        join[20, 7] = True
        z = jnp.zeros((30, cfg.n), dtype=bool)
        ev = RoundEvents(crash=jnp.asarray(crash), leave=z, join=jnp.asarray(join))

        base = run_rounds(init_state(cfg), cfg, 30, KEY, events=ev, crash_rate=0.01)
        mesh = make_mesh()
        st = shard_state(init_state(cfg), mesh)
        got = run_rounds_sharded(st, cfg, 30, KEY, mesh, events=ev, crash_rate=0.01)
        for a, b in zip(jax.tree.leaves(base), jax.tree.leaves(got)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert tuple(got[0].hb.sharding.spec) == (None, AXIS)

    def test_no_matrix_allgathers_on_pallas_path(self):
        """The whole point: the row gather must be shard-local, with only
        [N]-vector reductions crossing shards."""
        from gossipfs_tpu.parallel import mesh as pm
        from gossipfs_tpu.scenarios.schedule import FaultScenario
        from gossipfs_tpu.scenarios.tensor import compile_tensor

        cfg = SimConfig(n=1024, topology="random", fanout=8,
                        merge_kernel="pallas_interpret")
        m = make_mesh()
        st = shard_state(init_state(cfg), m)
        z = jnp.zeros((5, cfg.n), dtype=bool)
        from gossipfs_tpu.core.state import RoundEvents

        ev = RoundEvents(crash=z, leave=z, join=z)
        scn = compile_tensor(FaultScenario(name="none", n=cfg.n))
        fn = pm._sharded_runner(m, cfg, 0.0, 0.0, False)
        hlo = fn.lower(
            st.hb, st.age, st.status, st.alive, st.round, st.hb_base,
            ev.crash, ev.leave, ev.join, KEY, jnp.ones((cfg.n,), bool),
            scn,
        ).compile().as_text()
        assert "all-gather" not in hlo

    def test_non_lane_aligned_shard_falls_back_to_xla(self):
        """nloc=64 < the 128-lane tile: the pallas gate must see the local
        column count and fall back to the XLA path rather than crash."""
        from gossipfs_tpu.parallel.mesh import run_rounds_sharded

        cfg = SimConfig(n=512, topology="random", fanout=6,
                        merge_kernel="pallas_interpret")
        base = run_rounds(init_state(cfg), cfg, 10, KEY, crash_rate=0.02)
        mesh = make_mesh()
        st = shard_state(init_state(cfg), mesh)
        got = run_rounds_sharded(st, cfg, 10, KEY, mesh, crash_rate=0.02)
        for a, b in zip(jax.tree.leaves(base), jax.tree.leaves(got)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_ring_rejected(self):
        from gossipfs_tpu.parallel.mesh import run_rounds_sharded

        cfg = SimConfig(n=64, topology="ring", fanout=3)
        mesh = make_mesh()
        st = shard_state(init_state(cfg), mesh)
        with pytest.raises(ValueError, match="ring"):
            run_rounds_sharded(st, cfg, 5, KEY, mesh)

    @pytest.mark.slow  # interpreter-mode rr kernel per shard
    @pytest.mark.parametrize("topology,arc_align", [
        ("random_arc", 1), ("random", 1),
        # tile-aligned arcs (the round-5 headline/frontier topology): the
        # per-shard kernels run the group-max window path with global row
        # indices, and the sharded scan must stay bit-identical to the
        # single-chip aligned scan
        ("random_arc", 8),
    ])
    def test_sharded_rr_matches_single_chip(self, topology, arc_align):
        """Round-5: the RESIDENT-ROUND program itself in shard_map form —
        the same one-kernel round the single-chip headline runs, with the
        shard's column offset feeding the kernel's diagonal mask and only
        the [N]-vector member-count psum crossing shards.  Bit-identical
        states, carry, and per-round metrics vs the single-chip rr scan
        (which is itself fuzz-pinned to the XLA oracle)."""
        from gossipfs_tpu.parallel.mesh import run_rounds_sharded

        cfg = SimConfig(
            n=2048, topology=topology,
            fanout=16 if arc_align > 1 else 6, arc_align=arc_align,
            remove_broadcast=False,
            fresh_cooldown=True, t_cooldown=12, view_dtype="int8",
            hb_dtype="int8", merge_block_c=1024,
            merge_kernel="pallas_rr_interpret",
        )
        base = run_rounds(init_state(cfg), cfg, 6, KEY, crash_rate=0.02)
        mesh = make_mesh(2)  # nloc=1024 = one narrow stripe per shard
        st = shard_state(init_state(cfg), mesh)
        got = run_rounds_sharded(st, cfg, 6, KEY, mesh, crash_rate=0.02)
        for a, b in zip(jax.tree.leaves(base), jax.tree.leaves(got)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_no_matrix_allgathers_on_rr_path(self):
        """The sharded rr program must keep the row gather shard-local:
        no all-gather anywhere in its compiled HLO (the zero-all-gather
        assertion the projection paragraph cites, now on the rr form)."""
        from gossipfs_tpu.core.state import RoundEvents
        from gossipfs_tpu.parallel import mesh as pm
        from gossipfs_tpu.scenarios.schedule import FaultScenario
        from gossipfs_tpu.scenarios.tensor import compile_tensor

        cfg = SimConfig(
            n=2048, topology="random_arc", fanout=6, remove_broadcast=False,
            fresh_cooldown=True, t_cooldown=12, view_dtype="int8",
            hb_dtype="int8", merge_block_c=1024,
            merge_kernel="pallas_rr_interpret",
        )
        m = make_mesh(2)
        st = shard_state(init_state(cfg), m)
        z = jnp.zeros((3, cfg.n), dtype=bool)
        ev = RoundEvents(crash=z, leave=z, join=z)
        scn = compile_tensor(FaultScenario(name="none", n=cfg.n))
        fn = pm._sharded_runner(m, cfg, 0.02, 0.0, False,
                                matrix_events=False)
        hlo = fn.lower(
            st.hb, st.age, st.status, st.alive, st.round, st.hb_base,
            ev.crash, ev.leave, ev.join, KEY, jnp.ones((cfg.n,), bool),
            scn,
        ).compile().as_text()
        assert "all-gather" not in hlo


class TestPlacementBatch:
    def test_distinct_live_replicas(self):
        alive = jnp.ones((32,), dtype=bool).at[jnp.array([3, 4, 5])].set(False)
        out = np.asarray(place_batch(KEY, alive, n_files=50))
        assert out.shape == (50, 4)
        for row in out:
            assert len(set(row.tolist())) == 4
            assert not (set(row.tolist()) & {3, 4, 5})

    def test_underfull_cluster_pads_with_minus_one(self):
        alive = jnp.zeros((8,), dtype=bool).at[jnp.array([1, 2])].set(True)
        out = np.asarray(place_batch(KEY, alive, n_files=3))
        assert (out[:, :2] >= 0).all()
        assert (out[:, 2:] == -1).all()

    def test_roughly_uniform(self):
        alive = jnp.ones((16,), dtype=bool)
        out = np.asarray(place_batch(KEY, alive, n_files=2000))
        counts = np.bincount(out.ravel(), minlength=16)
        expected = 2000 * 4 / 16
        assert (np.abs(counts - expected) < expected * 0.25).all()
