"""gossipfs-spec completeness (gossipfs_tpu/analysis/protocol_spec.py).

The contract is itself held to the repo's surfaces, pure-AST where the
surface is a source file — no jax, no runtime:

  * every lifecycle kind in obs/schema.py LIFECYCLE_KINDS maps to a
    contract transition/injection emit and vice versa, so a new
    protocol state cannot ship without a contract row;
  * every contract emit is a declared EVENT_KINDS entry;
  * every transition references declared states, a THRESHOLDS guard
    formula, and a subset of the declared engines;
  * the wire-verb vocabulary equals the verbs the udp dispatch
    actually compares against (the socket wire's source of truth);
  * the drift-prone campaign dissemination row stays subject+fanout —
    the bound the round-17 satellite fix implements in both socket
    engines.
"""

from __future__ import annotations

import ast
import pathlib

from gossipfs_tpu.analysis import protocol_spec as spec

REPO = pathlib.Path(__file__).resolve().parents[1]


def _module_literal(path: str, name: str):
    tree = ast.parse((REPO / path).read_text())
    for node in tree.body:
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets
                       if isinstance(t, ast.Name)]
            value = node.value
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name):
            targets, value = [node.target.id], node.value
        else:
            continue
        if name in targets and value is not None:
            return ast.literal_eval(value)
    raise AssertionError(f"{path} has no module-level literal {name}")


def test_lifecycle_kinds_bijection():
    lifecycle = _module_literal("gossipfs_tpu/obs/schema.py",
                                "LIFECYCLE_KINDS")
    assert spec.lifecycle_emit_kinds() == set(lifecycle), (
        "obs/schema.py LIFECYCLE_KINDS and the contract's emit kinds "
        "must be the same set — add the protocol_spec row (or the "
        "schema kind) before shipping the other"
    )


def test_every_emit_is_a_declared_event_kind():
    kinds = _module_literal("gossipfs_tpu/obs/schema.py", "EVENT_KINDS")
    assert spec.lifecycle_emit_kinds() <= set(kinds)


def test_transitions_reference_declared_states_guards_engines():
    assert spec.TRANSITIONS, "the contract lost its transition table"
    for t in spec.TRANSITIONS:
        assert t.src in spec.STATES, t
        assert t.dst in spec.STATES, t
        assert t.guard in spec.THRESHOLDS, (
            f"transition {t.src}->{t.dst} guard `{t.guard}` has no "
            "THRESHOLDS formula"
        )
        assert set(t.engines) <= set(spec.ENGINES), t
    for i in spec.INJECTIONS:
        assert i.emits, i
    for r in spec.RATE_LIMITS:
        assert set(r.engines) <= set(spec.ENGINES), r
    for d in spec.DISSEMINATION:
        assert set(d.engines) <= set(spec.ENGINES), d


def test_wire_verbs_match_udp_dispatch():
    tree = ast.parse(
        (REPO / "gossipfs_tpu/detector/udp.py").read_text())
    handle = next(
        n for n in ast.walk(tree)
        if isinstance(n, ast.FunctionDef) and n.name == "handle")
    compared: set[str] = set()
    for node in ast.walk(handle):
        if not isinstance(node, ast.Compare):
            continue
        for comp in node.comparators:
            for sub in ast.walk(comp):
                if isinstance(sub, ast.Constant) \
                        and isinstance(sub.value, str) \
                        and sub.value.isupper():
                    compared.add(sub.value)
    assert compared == set(spec.WIRE_VERBS), (
        "the udp receive dispatch and the contract's WIRE_VERBS "
        f"disagree: dispatch={sorted(compared)} "
        f"contract={sorted(spec.WIRE_VERBS)}"
    )


def test_campaign_dissemination_row_stays_bounded():
    row = spec.dissemination_row("new_suspect", "campaign")
    assert row is not None
    assert row.bound == "subject+fanout"
    assert set(row.engines) == {"udp", "native"}
    assert row.annotated, (
        "the drift-prone row must require an explicit native "
        "@gfs:dissemination annotation"
    )


def test_refute_rate_limit_covers_both_socket_engines():
    limit = spec.rate_limit("refute_broadcast")
    assert limit is not None
    assert set(limit.engines) == {"udp", "native"}
