"""gossipfs-lint (gossipfs_tpu/analysis/ + tools/lint.py).

The analyzer is itself tested, not trusted:
  * every registered rule has a committed fixture under
    tests/fixtures/lint/ that makes it FIRE (mounted over the repo via
    the overlay index — nothing in the tree changes);
  * the repo itself is CLEAN under every rule (the tier-1 enforcement
    that replaced the scattered ad-hoc lint tests);
  * the CLI exits 0 on clean, 1 on findings, 2 on usage errors — the
    contract CI hooks rely on;
  * the native sanitizer/lint targets the round-15 satellite added stay
    present in native/Makefile (cheap fast-lane guard; the sanitizer
    RUNS ride the slow lane in tests/test_native_sanitizers.py).
"""

from __future__ import annotations

import json
import pathlib
import subprocess
import sys

import pytest

from gossipfs_tpu.analysis import REGISTRY, RepoIndex, run_rules
from gossipfs_tpu.analysis import probes

REPO = pathlib.Path(__file__).resolve().parents[1]
FIXTURES = REPO / "tests" / "fixtures" / "lint"

_AST_RULES = sorted(n for n, r in REGISTRY.items() if r.kind == "ast")


# ---------------------------------------------------------------------------
# registry completeness: every rule ships its trigger fixture
# ---------------------------------------------------------------------------


def test_every_rule_has_a_committed_fixture():
    for name, r in REGISTRY.items():
        assert r.fixture, f"rule {name} ships no fixture"
        assert (FIXTURES / r.fixture).is_file(), (name, r.fixture)
        if r.kind == "ast":
            assert r.fixture_at, f"ast rule {name} has no mount point"


# ---------------------------------------------------------------------------
# each rule fires on its fixture, and ONLY via its own name
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name", _AST_RULES)
def test_rule_fires_on_fixture(name):
    r = REGISTRY[name]
    idx = RepoIndex(overlay={r.fixture_at: FIXTURES / r.fixture})
    findings = r.check(idx)
    assert findings, f"rule {name} did not fire on its fixture"
    assert all(f.rule == name for f in findings)
    # the finding anchors to the mounted file (shadow mounts report the
    # virtual path), so a CI consumer can jump to the line
    assert any(f.path == r.fixture_at for f in findings), findings


def test_probe_rule_fires_on_injected_budget_drift():
    """The rr-scratch-budget probe reconciles RUNTIME allocations, so
    its committed fixture carries an injection knob instead of a mount:
    dropping the budget's last spec must break the byte-sum
    reconciliation."""
    ns: dict = {}
    exec((FIXTURES / "rr_scratch_budget.py").read_text(), ns)
    findings = probes._reconcile(spec_drop=ns["SPEC_DROP"])
    assert findings and all(f.rule == "rr-scratch-budget"
                            for f in findings)
    assert any("!= rr_align_scratch_bytes" in f.message for f in findings)


# ---------------------------------------------------------------------------
# the repo runs clean — the actual enforcement
# ---------------------------------------------------------------------------


def test_repo_clean_under_all_ast_rules():
    # (the rr-scratch-budget probe's clean run stays where it always
    # lived — tests/test_merge_pallas.py::test_rr_scratch_budget_lint,
    # now a thin wrapper over analysis.probes)
    findings = run_rules(RepoIndex())
    assert not findings, "\n".join(str(f) for f in findings)


# ---------------------------------------------------------------------------
# CLI contract (the tier-1 fast-lane invocation of tools/lint.py)
# ---------------------------------------------------------------------------


def _cli(*args):
    return subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint.py"), *args],
        capture_output=True, text=True, cwd=REPO, timeout=120)


def test_cli_clean_repo_exits_zero():
    out = _cli()
    assert out.returncode == 0, out.stdout + out.stderr


def test_cli_lists_every_rule():
    out = _cli("--list")
    assert out.returncode == 0
    for name in REGISTRY:
        assert name in out.stdout, name


def test_cli_exits_nonzero_on_findings_and_emits_json():
    overlay = ("gossipfs_tpu/traffic/_lint_fixture.py="
               "tests/fixtures/lint/quorum_ownership.py")
    out = _cli("--overlay", overlay, "--json")
    assert out.returncode == 1, out.stdout + out.stderr
    findings = json.loads(out.stdout)
    assert any(f["rule"] == "quorum-ownership" for f in findings)
    # rule subsetting keeps the exit-code contract
    out = _cli("--overlay", overlay, "--rule", "quorum-ownership")
    assert out.returncode == 1
    out = _cli("--overlay", overlay, "--rule", "backoff-ownership")
    assert out.returncode == 0


def test_cli_usage_errors_exit_two():
    assert _cli("--rule", "no-such-rule").returncode == 2
    assert _cli("--overlay", "missing-equals").returncode == 2
    # internal errors (unreadable/unparseable overlay) are 2 as well —
    # never 1, which a CI hook would read as "findings exist"
    assert _cli("--overlay",
                "gossipfs_tpu/traffic/_x.py=/nonexistent.py",
                "--rule", "quorum-ownership").returncode == 2


# ---------------------------------------------------------------------------
# native satellite: the sanitizer/lint targets stay wired
# ---------------------------------------------------------------------------


def test_native_makefile_has_sanitizer_targets():
    mk = (REPO / "native" / "Makefile").read_text()
    for target in ("tsan:", "asan:", "lint-native:", "tsa:"):
        assert target in mk, f"native/Makefile lost the {target} target"
    assert (REPO / "native" / ".clang-tidy").is_file()
    assert (REPO / "native" / "sanitize_main.cc").is_file()
    assert (REPO / "native" / "tsa.h").is_file()
