"""Multi-process (DCN-path) bring-up: jax.distributed over 2 CPU processes.

Round 1 shipped ``parallel/distributed.py`` untested.  This spawns two real
Python processes that rendezvous through the env-driven ``initialize()``
path (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID), build
the global 2-device mesh, run the sharded scan — whose collectives now
actually cross process boundaries — and verify each process's addressable
shard bit-matches the single-device reference run.
"""

from __future__ import annotations

import os
import pytest
import socket
import subprocess
import sys

# spawns real multi-process DCN rendezvous runs
pytestmark = pytest.mark.slow

WORKER = r"""
import os

# initialize the distributed runtime FIRST: several gossipfs modules build
# jnp constants at import, and jax.distributed refuses to start after the
# first computation
from gossipfs_tpu.parallel import distributed

ok = distributed.initialize()  # env-driven branch (the untested round-1 path)
assert ok, "expected distributed mode from env vars"

import numpy as np

import jax
import jax.numpy as jnp

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.core.rounds import run_rounds
from gossipfs_tpu.core.state import RoundEvents, init_state
from gossipfs_tpu.parallel.mesh import run_rounds_sharded, state_shardings


assert jax.process_count() == 2
mesh = distributed.global_mesh()
assert mesh.devices.size == 2

cfg = SimConfig(n=256, topology="random", fanout=6)
rounds = 15
crash = np.zeros((rounds, cfg.n), dtype=bool)
crash[3, 7] = True
z = jnp.zeros((rounds, cfg.n), dtype=bool)
ev = RoundEvents(crash=jnp.asarray(crash), leave=z, join=z)
key = jax.random.PRNGKey(11)

state = jax.jit(lambda: init_state(cfg), out_shardings=state_shardings(mesh))()
got, mc, pr = run_rounds_sharded(state, cfg, rounds, key, mesh, events=ev)

ref, mc_ref, pr_ref = run_rounds(init_state(cfg), cfg, rounds, key, events=ev)
for arr, full in ((got.hb, ref.hb), (got.status, ref.status), (got.age, ref.age)):
    want = np.asarray(full)
    for shard in arr.addressable_shards:
        np.testing.assert_array_equal(np.asarray(shard.data), want[shard.index])
print("DIST-OK", jax.process_index(), flush=True)
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_cpu_mesh(tmp_path):
    port = _free_port()
    env_base = dict(os.environ)
    env_base.pop("PALLAS_AXON_POOL_IPS", None)
    env_base.update(
        JAX_PLATFORMS="cpu",
        JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
        JAX_NUM_PROCESSES="2",
        # one device per process (the parent test env forces 8 virtual
        # devices, which would make the global mesh 16-wide)
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
    )
    procs = []
    for pid in range(2):
        env = dict(env_base, JAX_PROCESS_ID=str(pid))
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", WORKER],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            )
        )
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=420)
        outs.append((p.returncode, out.decode(), err.decode()))
    for rc, out, err in outs:
        assert rc == 0, f"worker failed:\n{err[-2000:]}"
    assert "DIST-OK 0" in outs[0][1]
    assert "DIST-OK 1" in outs[1][1]
