"""Conformance-fuzzing subsystem (gossipfs_tpu/conformance/).

Fast lane: generator round-trip + seed determinism over every family,
contract coverage, the reference-oracle selfcheck sweep, shrink
mechanics on a pure predicate, the codec-hardening unit, one short
schedule through reference + tensor + udp with verdict agreement, and
the committed malformed-datagram minimal repro replayed end-to-end
(the fuzzer-found regression, post-fix green).  Slow lane: the native
C++ engine column.
"""

from __future__ import annotations

import json
import pathlib

import pytest

from gossipfs_tpu.conformance import harness, schedules, shrink, verdict

pytestmark = pytest.mark.conformance

REPO = pathlib.Path(__file__).resolve().parent.parent
REPRO = REPO / "regressions" / "conformance_malformed_udp.json"


# ---------------------------------------------------------------------------
# generator: round-trip, determinism, coverage, validation
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("family", sorted(schedules.FAMILIES))
def test_round_trip(family):
    case = schedules.generate(family, seed=0)
    schedules.validate(case)
    text = schedules.serialize(case)
    assert schedules.serialize(schedules.parse(text)) == text


@pytest.mark.parametrize("family", sorted(schedules.FAMILIES))
def test_seed_determinism(family):
    a = schedules.serialize(schedules.generate(family, seed=3))
    b = schedules.serialize(schedules.generate(family, seed=3))
    assert a == b  # byte-identical: the corpus is replayable from seeds


def test_coverage_complete():
    from gossipfs_tpu.analysis import protocol_spec as spec

    cov = schedules.coverage()
    assert cov["complete"], cov
    assert not cov["verbs_missing"]
    assert not cov["injections_missing"]
    assert not cov["transitions_missing"]
    # the corpus covers the CONTRACT's sets, not a private copy
    assert set(cov["verbs"]) == set(spec.WIRE_VERBS)
    assert set(cov["injections"]) == {i.name for i in spec.INJECTIONS}


def test_validate_rejects_drift():
    case = schedules.generate("confirm_expiry", seed=0)
    bad = dict(case, schema="gossipfs-conformance/v2")
    with pytest.raises(ValueError):
        schedules.validate(bad)
    bad = json.loads(schedules.serialize(case))
    bad = schedules.parse(json.dumps(bad))
    bad["steps"] = [{"round": 1, "op": "frobnicate", "node": 1}]
    with pytest.raises(ValueError):
        schedules.validate(bad)
    bad = schedules.parse(schedules.serialize(case))
    bad["expect"][str(case["tracked"][0])]["final"] = "zombie"
    with pytest.raises(ValueError):
        schedules.validate(bad)


# ---------------------------------------------------------------------------
# reference oracle: every family's prediction matches its declaration
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("family", sorted(schedules.FAMILIES))
def test_oracle_selfcheck(family):
    case = schedules.generate(family, seed=0)
    ref = harness.run_case_reference(case)
    row = verdict.oracle_selfcheck(case, ref)
    assert row["ok"], row["checks"]["oracle_selfcheck"]["problems"]


# ---------------------------------------------------------------------------
# codec hardening (the round-19 fuzzer-found udp fix)
# ---------------------------------------------------------------------------


def test_udp_decode_skips_bad_entries():
    """One malformed chunk must not abort the datagram: the valid
    entries sharing it still merge (native DecodeMembers semantics —
    the asymmetry the malformed_codec family caught)."""
    from gossipfs_tpu.detector.udp import ENTRY_SEP, FIELD_SEP, UdpNode

    good = f"127.0.0.1:9001{FIELD_SEP}7{FIELD_SEP}0.0"
    bad = f"x{FIELD_SEP}notanumber{FIELD_SEP}0.0"
    out = UdpNode._decode(ENTRY_SEP.join([bad, good, f"y{FIELD_SEP}"]))
    assert out == [("127.0.0.1:9001", 7, 0.0)]


# ---------------------------------------------------------------------------
# shrink mechanics (pure predicate — no sockets)
# ---------------------------------------------------------------------------


def test_shrink_minimizes_to_predicate():
    case = schedules.generate("malformed_codec", seed=0)

    def still_fails(cand):
        return any(s["op"] == "crash" for s in cand["steps"])

    small = shrink.shrink(case, still_fails, settle_pad=2)
    assert [s["op"] for s in small["steps"]] == ["crash"]
    assert not small["checkpoints"]
    assert small["rounds"] < case["rounds"]
    schedules.validate(small)


def test_shrink_requires_failing_start():
    case = schedules.generate("confirm_expiry", seed=0)
    with pytest.raises(ValueError):
        shrink.shrink(case, lambda cand: False)


def test_shrink_seed_neighbourhood_canonicalizes():
    """The seed pass restarts ddmin from the smallest failing draw in
    the neighbourhood — with size-tied draws that means the lowest
    failing seed, here 1 (seed 0 passes, so it may not be adopted)."""
    case = schedules.generate("malformed_codec", seed=3)

    def still_fails(cand):
        return (cand.get("seed", 0) >= 1
                and any(s["op"] == "crash" for s in cand["steps"]))

    small = shrink.shrink(case, still_fails, settle_pad=2)
    assert small["seed"] == 1
    assert [s["op"] for s in small["steps"]] == ["crash"]
    schedules.validate(small)


def test_shrink_seed_radius_zero_disables_pass():
    case = schedules.generate("malformed_codec", seed=3)

    def still_fails(cand):
        return any(s["op"] == "crash" for s in cand["steps"])

    small = shrink.shrink(case, still_fails, settle_pad=2,
                          seed_radius=0)
    assert small["seed"] == 3


# ---------------------------------------------------------------------------
# fast-lane engine smoke: one short schedule, three surfaces agreeing
# ---------------------------------------------------------------------------


def test_smoke_reference_tensor_udp():
    case = schedules.generate("leave_broadcast", seed=0)
    ref = harness.run_case_reference(case)
    assert verdict.oracle_selfcheck(case, ref)["ok"]
    for runner in (harness.run_case_tensor, harness.run_case_udp):
        row = verdict.compare(case, ref, runner(case))
        assert row["ok"], (row["engine"], row["checks"])


def test_regression_replay_malformed_udp():
    """The committed fuzzer-found minimal repro (crash + one
    mixed_refresh malformed datagram) replays green on the fixed
    decode — exactly like the campaign storm-case replays."""
    case = schedules.parse(REPRO.read_text(encoding="utf-8"))
    assert case["family"] == "malformed_codec"
    assert any(s["op"] == "malformed" for s in case["steps"])
    ref = harness.run_case_reference(case)
    # the doc's declared expectation matches its own oracle (shrink
    # resyncs it after rounds minimization — a repro whose selfcheck
    # fails blames the generator instead of the engine it indicts)
    assert verdict.oracle_selfcheck(case, ref)["ok"]
    row = verdict.compare(case, ref, harness.run_case_udp(case))
    assert row["ok"], row["checks"]


def test_artifact_contract():
    """CONFORMANCE_r19.json stays evidence-shaped: the full matrix all
    agreeing over every engine column, contract coverage complete, and
    the divergence block a genuine red->green pair (the pre-fix udp run
    RECORDED failing, the post-fix twin passing)."""
    doc = json.loads(
        (REPO / "CONFORMANCE_r19.json").read_text(encoding="utf-8"))
    assert doc["schema"] == "gossipfs-conformance-evidence/v1"
    m = doc["matrix"]
    assert m["schema"] == "gossipfs-conformance-matrix/v1"
    assert m["all_agree"] and not m["disagreements"]
    assert m["coverage"]["complete"]
    assert set(m["engines_run"]) == {"reference", "tensor", "udp", "native"}
    assert m["cases"] == len(schedules.FAMILIES)
    div = doc["divergence"]
    assert div["red"]["engine"] == "udp"
    assert div["red"]["family"] == "malformed_codec"
    assert not div["red"]["ok"] and div["green"]["ok"]
    assert (REPO / div["minimized"]).is_file()


# ---------------------------------------------------------------------------
# slow lane: the native C++ epoll column
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_smoke_native():
    case = schedules.generate("confirm_expiry", seed=0)
    ref = harness.run_case_reference(case)
    row = verdict.compare(case, ref, harness.run_case_native(case))
    assert row["ok"], row["checks"]


@pytest.mark.slow
def test_native_repro_agrees():
    """The same minimal repro on the native engine: its codec always
    skipped bad entries, so it was green before the udp fix and stays
    green after."""
    case = schedules.parse(REPRO.read_text(encoding="utf-8"))
    ref = harness.run_case_reference(case)
    row = verdict.compare(case, ref, harness.run_case_native(case))
    assert row["ok"], row["checks"]
