"""Suspicion subsystem: SWIM suspect/refute lifecycle + Lifeguard
adaptive timeouts across the three transport engines
(gossipfs_tpu/suspicion/ — see ISSUE/BASELINE "Suspicion").

Fast lane: params schema + config gating, the tensor lifecycle
(crash -> SUSPECT -> FAILED with the t_suspect window; blackout ->
SUSPECT -> refuted with zero false positives), deterministic
tensor-vs-oracle parity (including local health), sim-vs-UDP engine
parity on the same scenario file (confirm and refute cases), the CLI
verbs, and a tier-1 smoke.  Slow lane: the per-process deploy variant
(params pushed over the control plane, vitals riding ScenarioStatus).
"""

import asyncio
import io
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.core.state import SUSPECT, RoundEvents, init_state
from gossipfs_tpu.scenarios import FaultScenario, LinkFault, split_halves
from gossipfs_tpu.suspicion import (
    SuspicionParams,
    SuspicionRuntime,
    require_suspicion_config,
    with_suspicion,
)

pytestmark = pytest.mark.suspicion


def sus_cfg(n: int, t_fail: int = 3, t_suspect: int = 3, **over) -> SimConfig:
    kw = dict(
        n=n, topology="random", fanout=SimConfig.log_fanout(n),
        remove_broadcast=False, fresh_cooldown=True, t_cooldown=6,
        t_fail=t_fail,
    )
    kw.update(over)
    return with_suspicion(SimConfig(**kw), SuspicionParams(t_suspect=t_suspect))


def crash_events(n: int, rounds: int, node: int, at: int) -> RoundEvents:
    crash = np.zeros((rounds, n), dtype=bool)
    crash[at, node] = True
    z = jnp.zeros((rounds, n), dtype=bool)
    return RoundEvents(crash=jnp.asarray(crash), leave=z, join=z)


# ---------------------------------------------------------------------------
# schema + gating
# ---------------------------------------------------------------------------


class TestParams:
    def test_json_roundtrip_and_validation(self):
        p = SuspicionParams(t_suspect=4, lh_multiplier=2, lh_frac=0.125)
        assert SuspicionParams.from_json(p.to_json()) == p
        assert p.confirm_after(5) == 9
        assert p.confirm_after(5, degraded=True) == 17
        assert p.max_confirm_after(5) == 17
        with pytest.raises(ValueError, match="t_suspect"):
            SuspicionParams(t_suspect=0)
        with pytest.raises(ValueError, match="lh_frac"):
            SuspicionParams(lh_frac=1.5)

    def test_config_gating(self):
        # broadcast mode: the REMOVE column-OR would bypass the window
        with pytest.raises(ValueError, match="remove_broadcast"):
            require_suspicion_config(SimConfig(n=16))
        with pytest.raises(ValueError, match="gossip-only"):
            SimConfig(n=16, suspicion=SuspicionParams())
        # round 11: the old merge_kernel="xla" / elementwise="lanes"
        # construction gates are GONE — the lifecycle is fused into every
        # merge path, so fast-kernel + suspicion configs construct
        fast = SimConfig(n=2048, topology="random", fanout=11,
                         remove_broadcast=False, fresh_cooldown=True,
                         merge_kernel="pallas", view_dtype="int8",
                         hb_dtype="int16", suspicion=SuspicionParams())
        assert fast.merge_kernel == "pallas"
        swar = SimConfig(n=1024, topology="random", fanout=10,
                         remove_broadcast=False, fresh_cooldown=True,
                         hb_dtype="int8", view_dtype="int8",
                         elementwise="swar", suspicion=SuspicionParams())
        assert swar.elementwise == "swar"
        # the production fast-path profile: rr/SWAR at a capacity shape
        rr = SimConfig.suspicion_rr(65_536)
        assert rr.merge_kernel == "pallas_rr"
        assert rr.suspicion is not None
        # the age lane carries the suspicion clock: it must not saturate
        with pytest.raises(ValueError, match="AGE_CLAMP"):
            SimConfig(n=64, topology="random", fanout=6,
                      remove_broadcast=False, fresh_cooldown=True,
                      t_fail=30, t_cooldown=12,
                      suspicion=SuspicionParams(t_suspect=40))

    def test_with_suspicion_substitutes_fast_kernels(self):
        fast = SimConfig(n=2048, topology="random", fanout=11,
                         remove_broadcast=False, fresh_cooldown=True,
                         merge_kernel="pallas", view_dtype="int8",
                         hb_dtype="int16", merge_block_c=1024)
        cfg = with_suspicion(fast, SuspicionParams(t_suspect=2))
        assert cfg.merge_kernel == "xla"
        assert cfg.suspicion == SuspicionParams(t_suspect=2)
        assert (cfg.t_fail, cfg.hb_dtype, cfg.view_dtype) == (
            fast.t_fail, fast.hb_dtype, fast.view_dtype)

    def test_runtime_lifecycle(self):
        rt = SuspicionRuntime(SuspicionParams(t_suspect=2, lh_multiplier=3,
                                              lh_frac=0.25))
        assert rt.suspect("a", 10.0) and not rt.suspect("a", 11.0)
        assert not rt.expired("a", 11.9, 2.0)
        assert rt.expired("a", 12.1, 2.0)
        assert rt.refute("a") and not rt.refute("a")
        rt.suspect("b", 0.0)
        rt.confirm("b")
        assert rt.refutations == 1 and rt.confirms == 1
        # local health: 1 suspect of 2 listed > 0.25 -> window stretches
        rt.suspect("c", 0.0)
        assert rt.degraded(2) and rt.t_suspect_window(1.0, 2) == 8.0
        assert not rt.degraded(8)
        st = rt.status()
        assert st["suspects"] == ["c"] and st["refutations"] == 1


# ---------------------------------------------------------------------------
# tensor engine lifecycle (the fast-lane tier-1 smoke lives here too)
# ---------------------------------------------------------------------------


class TestTensorLifecycle:
    def test_crash_suspect_then_confirm(self):
        """A real crash walks the whole lifecycle: SUSPECT at t_fail
        silence, FAILED t_suspect rounds later, cluster-wide convergence
        after — and the carries/metrics see each stage."""
        from gossipfs_tpu.core.rounds import run_rounds
        from gossipfs_tpu.metrics.detection import summarize

        n, rounds, victim, at = 64, 30, 7, 5
        cfg = sus_cfg(n, t_fail=3, t_suspect=3)
        final, mc, per = run_rounds(
            init_state(cfg), cfg, rounds, jax.random.PRNGKey(0),
            events=crash_events(n, rounds, victim, at),
        )
        report = summarize(mc, per, {victim: at})
        # suspected ~t_fail+1 rounds after the crash, confirmed exactly
        # t_suspect later (the age lane is the clock, so the gap is tight)
        assert 3 <= report.ttd_suspect[victim] <= 5
        assert report.suspect_to_confirm[victim] == 3
        assert report.ttd_first[victim] == report.ttd_suspect[victim] + 3
        assert report.ttd_converged[victim] >= report.ttd_first[victim]
        assert report.true_detections > 0
        # the victim ends FAILED/UNKNOWN everywhere, never re-added
        st = np.asarray(final.status)
        alive = np.asarray(final.alive)
        assert not alive[victim]
        assert (st[alive][:, victim] != 1).all()
        assert (st[alive][:, victim] != int(SUSPECT)).all()

    def test_blackout_refutes_before_confirm(self):
        """The acceptance refutation case: a LIVE node whose outgoing
        gossip blacks out past t_fail is SUSPECTED everywhere; the
        blackout heals inside the t_suspect window, the node's own
        (kept-bumping) counter floods back, and every pending failure is
        cancelled — zero false positives, zero confirmations."""
        from gossipfs_tpu.core.rounds import run_rounds
        from gossipfs_tpu.scenarios.tensor import compile_tensor

        n, rounds, victim = 64, 25, 9
        cfg = sus_cfg(n, t_fail=3, t_suspect=8)
        # total outbound blackout over [2, 8): ages reach ~6 > t_fail
        # but stay under the confirm threshold 11
        sc = FaultScenario(
            name="blackout", n=n,
            link_faults=(LinkFault(start=2, end=8, rate=1.0,
                                   src=(victim,), dst=tuple(range(n))),),
        )
        final, mc, per = run_rounds(
            init_state(cfg), cfg, rounds, jax.random.PRNGKey(1),
            scenario=compile_tensor(sc),
        )
        assert int(np.asarray(per.suspects_entered).sum()) > 0
        assert int(np.asarray(per.refutations).sum()) > 0
        assert int(np.asarray(per.fp_suppressed).sum()) > 0
        # the pending failure was cancelled: never confirmed, no FPs;
        # the fully-refuted episode also RESETS the suspect clock, so a
        # later real crash would measure its own episode, not this one
        assert int(mc.first_detect[victim]) == -1
        assert int(mc.first_suspect[victim]) == -1
        assert int(np.asarray(per.false_positives).sum()) == 0
        assert int(np.asarray(per.true_detections).sum()) == 0
        # fully healed membership
        assert (np.asarray(final.status) == 1).all()

    def test_suspect_counts_toward_membership(self):
        """SUSPECT entries are still members: views, gossip eligibility
        and convergence all treat them as listed (the detector seam's
        membership() includes them)."""
        from gossipfs_tpu.detector.sim import SimDetector
        from gossipfs_tpu.scenarios.tensor import compile_tensor

        n, victim = 32, 3
        cfg = sus_cfg(n, t_fail=3, t_suspect=10)
        det = SimDetector(cfg, seed=0)
        # blackout starts at round 2, once counters cleared the hb<=1
        # detection grace (slave.go:468) — a never-heard-from node is
        # grace-protected and cannot be suspected at all
        sc = FaultScenario(
            name="blackout", n=n,
            link_faults=(LinkFault(start=2, end=30, rate=1.0,
                                   src=(victim,), dst=tuple(range(n))),),
        )
        det.load_scenario(sc)
        det.advance(9)  # past t_fail silence: suspected, far from confirm
        sus = det.suspects(0)
        assert victim in sus
        assert victim in det.membership(0)  # still a member
        st = det.suspicion_status()
        assert st["enabled"] and st["suspects_now"] > 0
        assert st["suspect_counts"]  # per-node counts present

    def test_oracle_parity_deterministic_with_local_health(self):
        """Fast-lane golden parity: the XLA suspicion lifecycle (with the
        Lifeguard stretch armed) against the per-node oracle, driven by a
        deterministic crash/leave/join schedule through the zombie-rejoin
        corner.  The randomized sweep lives in the slow-lane golden fuzz."""
        import sys

        sys.path.insert(0, "tests")
        from reference_model import NaiveSim

        from gossipfs_tpu.core import topology
        from gossipfs_tpu.core.rounds import gossip_round

        n = 32
        base = SimConfig(n=n, topology="random", fanout=5,
                         remove_broadcast=False, fresh_cooldown=True,
                         t_fail=3, t_cooldown=5)
        cfg = with_suspicion(base, SuspicionParams(
            t_suspect=2, lh_multiplier=2, lh_frac=0.25))
        schedule = {
            4: dict(crash=[1, 2, 3, 4, 5, 6, 7, 8, 9]),  # mass death ->
            # surviving views cross lh_frac: the stretch path runs
            10: dict(leave=[10]),
            12: dict(join=[3]),   # rejoin while others still suspect it
            20: dict(crash=[11]),
            26: dict(join=[11]),
        }
        state = init_state(cfg)
        naive = NaiveSim(cfg)
        key = jax.random.PRNGKey(7)
        for r in range(40):
            ev = schedule.get(r, {})
            def m(idx):
                a = np.zeros(n, dtype=bool)
                if idx:
                    a[list(idx)] = True
                return jnp.asarray(a)
            events = RoundEvents(crash=m(ev.get("crash")),
                                 leave=m(ev.get("leave")),
                                 join=m(ev.get("join")))
            k = jax.random.fold_in(key, r)
            edges = topology.in_edges(cfg, k, None)
            state, _, _, _ = gossip_round(state, events, edges, cfg)
            naive.step(np.array(edges), crash=ev.get("crash", []),
                       leave=ev.get("leave", []), join=ev.get("join", []))
            hb = np.array(state.hb_true())
            age = np.array(state.age)
            status = np.array(state.status)
            assert np.array(state.alive).tolist() == naive.alive, f"r{r}"
            for i in range(n):
                if not naive.alive[i]:
                    continue
                for j in range(n):
                    e = naive.tables[i][j]
                    assert status[i][j] == e.status, f"status[{i},{j}] r{r}"
                    if e.status != 0:
                        zombie = e.hb > naive.tables[j][j].hb
                        if not zombie:
                            assert hb[i][j] == e.hb, f"hb[{i},{j}] r{r}"
                        assert age[i][j] == e.age, f"age[{i},{j}] r{r}"

    def test_reference_mode_unreachable(self):
        """Without suspicion armed the SUSPECT lane value never appears
        and the suspicion metrics stay zero — the reference mode is
        bit-unchanged (the golden tests pin this too; here it's cheap)."""
        from gossipfs_tpu.core.rounds import run_rounds

        n, rounds = 32, 15
        cfg = SimConfig(n=n, topology="random", fanout=5,
                        remove_broadcast=False, fresh_cooldown=True)
        final, mc, per = run_rounds(
            init_state(cfg), cfg, rounds, jax.random.PRNGKey(0),
            events=crash_events(n, rounds, 5, 3),
        )
        assert (np.asarray(final.status) != int(SUSPECT)).all()
        assert int(np.asarray(per.suspects_entered).sum()) == 0
        assert int(np.asarray(per.refutations).sum()) == 0
        assert (np.asarray(mc.first_suspect) == -1).all()


# ---------------------------------------------------------------------------
# engine parity: one policy, same lifecycle events, sim vs UDP (fast lane)
# ---------------------------------------------------------------------------


class TestEngineParity:
    def test_partition_confirm_parity_sim_vs_udp(self):
        """A never-healing partition under suspicion: both engines walk
        each cross-side entry SUSPECT -> FAILED (same confirmed subject
        sets, zero same-side confirms, suspicion observed before the
        confirms) and end fully split."""
        from gossipfs_tpu.detector.sim import SimDetector
        from gossipfs_tpu.detector.udp import UdpCluster

        n = 10
        side_a, side_b = set(range(5)), set(range(5, 10))
        sc = split_halves(n, start=5, end=1000)
        params = SuspicionParams(t_suspect=3)

        # -- tensor sim (ring parity mode, gossip-only + suspicion)
        cfg = with_suspicion(
            SimConfig(n=n, remove_broadcast=False, fresh_cooldown=True,
                      t_cooldown=6),
            params,
        )
        det = SimDetector(cfg, seed=0)
        det.load_scenario(sc)
        saw_suspects = False
        for _ in range(8):
            det.advance(5)
            st = det.suspicion_status()
            saw_suspects = saw_suspects or st["suspects_now"] > 0
        sim_events = det.drain_events()
        sim_views = {i: set(det.membership(i)) for i in range(n)}
        assert saw_suspects and det.suspicion_status()["confirms"] > 0

        # -- asyncio UDP engine, same scenario + same params
        async def udp_run():
            c = UdpCluster(n=n, base_port=23800, period=0.05,
                           fresh_cooldown=True, scenario=sc,
                           suspicion=params)
            try:
                await c.start_all()
                saw = False
                for _ in range(8):
                    await c.run(5)
                    st = c.suspicion_status()
                    saw = saw or st["suspects_now"] > 0
                return (c.drain_events(),
                        {i: set(c.membership(i)) for i in c.alive_nodes()},
                        saw, c.suspicion_status())
            finally:
                c.stop_all()

        udp_events, udp_views, udp_saw, udp_status = asyncio.run(udp_run())
        assert udp_saw and udp_status["confirms"] > 0

        for name, events, views in (("sim", sim_events, sim_views),
                                    ("udp", udp_events, udp_views)):
            det_by_a = {e.subject for e in events if e.observer in side_a}
            det_by_b = {e.subject for e in events if e.observer in side_b}
            assert det_by_a == side_b, (name, det_by_a)
            assert det_by_b == side_a, (name, det_by_b)
            for i, view in views.items():
                assert view == (side_a if i in side_a else side_b), (
                    name, i, view)

    def test_heal_refute_parity_sim_vs_udp(self):
        """The partition heals inside the SUSPECT window: both engines
        refute every pending failure — zero confirmations, refutation
        counts positive, views fully knit back.  End-to-end refutation
        in BOTH engines (the acceptance criterion's 'at least one')."""
        from gossipfs_tpu.detector.sim import SimDetector
        from gossipfs_tpu.detector.udp import UdpCluster

        n = 10
        # split [3, 10): ages reach ~7 > t_fail=3; confirm would need
        # > 3 + 8 = 11 silent rounds — heal at 7 rounds refutes first
        sc = split_halves(n, start=3, end=10)
        params = SuspicionParams(t_suspect=8)

        cfg = with_suspicion(
            SimConfig(n=n, remove_broadcast=False, fresh_cooldown=True,
                      t_cooldown=6, t_fail=3),
            params,
        )
        det = SimDetector(cfg, seed=0)
        det.load_scenario(sc)
        det.advance(30)
        st = det.suspicion_status()
        assert det.drain_events() == []          # nothing ever confirmed
        assert st["refutations"] > 0 and st["confirms"] == 0
        assert all(set(det.membership(i)) == set(range(n))
                   for i in range(n))

        async def udp_run():
            c = UdpCluster(n=n, base_port=23900, period=0.05,
                           fresh_cooldown=True, t_fail=3, scenario=sc,
                           suspicion=params)
            try:
                await c.start_all()
                await c.run(30)
                return (c.drain_events(), c.suspicion_status(),
                        {i: set(c.membership(i)) for i in c.alive_nodes()})
            finally:
                c.stop_all()

        udp_events, udp_status, udp_views = asyncio.run(udp_run())
        assert udp_events == []
        assert udp_status["refutations"] > 0 and udp_status["confirms"] == 0
        assert all(v == set(range(n)) for v in udp_views.values())


# ---------------------------------------------------------------------------
# CLI verbs (shim/cli.py satellite)
# ---------------------------------------------------------------------------


class TestCliVerbs:
    def _sim(self, n=16):
        from gossipfs_tpu.cosim import CoSim

        cfg = sus_cfg(n, t_fail=3, t_suspect=10)
        return CoSim(cfg, seed=0)

    def test_suspicion_status_verb_and_lsm_marks(self):
        from gossipfs_tpu.scenarios.tensor import compile_tensor  # noqa: F401
        from gossipfs_tpu.shim import cli

        sim = self._sim()
        victim = 3
        # start past the hb<=1 grace so the blackout victim is suspectable
        sc = FaultScenario(
            name="blackout", n=16,
            link_faults=(LinkFault(start=2, end=40, rate=1.0,
                                   src=(victim,),
                                   dst=tuple(range(16))),),
        )
        sim.load_scenario(sc)
        sim.tick(9)
        out = io.StringIO()
        cli.dispatch(sim, "suspicion status", out=out)
        text = out.getvalue()
        assert "refutations=" in text and "suspect entries now" in text
        out2 = io.StringIO()
        cli.dispatch(sim, "lsm 0", out=out2)
        assert f"{victim}?" in out2.getvalue()  # SUSPECT rendered distinctly

    def test_status_verb_without_suspicion(self):
        from gossipfs_tpu.cosim import CoSim
        from gossipfs_tpu.shim import cli

        sim = CoSim(SimConfig(n=8, remove_broadcast=False,
                              fresh_cooldown=True), seed=0)
        out = io.StringIO()
        cli.dispatch(sim, "suspicion status", out=out)
        assert "no suspicion armed" in out.getvalue()

    def test_t_suspect_flag(self):
        from gossipfs_tpu.shim import cli

        args = cli.make_parser().parse_args(
            ["--n", "8", "--gossip-only", "--t-suspect", "4"])
        assert args.t_suspect == 4

    def test_packed_t_suspect_composes(self):
        """Round 11 lifted the CLI's --packed/--t-suspect guard: the rr
        kernel runs the lifecycle natively, so arming suspicion on the
        packed profile is a plain field set that keeps the fast kernel
        (no oracle substitution) and passes __post_init__'s
        protocol-mode check (packed_rr is gossip-only already)."""
        import dataclasses

        from gossipfs_tpu.shim import cli

        args = cli.make_parser().parse_args(
            ["--n", "2048", "--packed", "--t-suspect", "2"])
        cfg = dataclasses.replace(
            SimConfig.packed_rr(args.n),
            suspicion=SuspicionParams(t_suspect=args.t_suspect))
        assert cfg.merge_kernel == "pallas_rr"
        assert cfg.suspicion is not None and cfg.suspicion.t_suspect == 2


# ---------------------------------------------------------------------------
# deploy variant (slow lane): params over the control plane, real processes
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_deploy_suspicion_lifecycle(tmp_path):
    """The per-process deployment under the same suspicion policy: the
    launcher pushes SuspicionParams over the control plane, a kill -9
    victim is SUSPECTED (visible in the ScenarioStatus vitals) before the
    confirm removes it the protocol way; and a brief partition heals into
    REFUTATIONS instead of removals."""
    from gossipfs_tpu.deploy.launcher import Cluster
    from gossipfs_tpu.scenarios import Partition

    n = 6
    cluster = Cluster(n, period=0.1, root=str(tmp_path), t_fail=5)
    try:
        cluster.start(timeout=90.0)
        # t_suspect=15 at period 0.1 -> a ~1.5 s observable SUSPECT window
        acked = cluster.load_suspicion(SuspicionParams(t_suspect=15))
        assert set(acked) == set(range(n))
        status = cluster.scenario_status()
        assert len(status) == n and all(
            ln["suspicion_armed"] for ln in status)

        # -- refutation via a brief partition: [0,1] cut off for ~1 s
        # (past t_fail, inside t_suspect), then healed
        side = (0, 1)
        sc = FaultScenario(
            name="brief-split", n=n,
            partitions=(Partition(start=0, end=10, groups=(side,)),),
        )
        cluster.load_scenario(sc)
        deadline = time.monotonic() + 60.0
        refuted = False
        while time.monotonic() < deadline and not refuted:
            lines = cluster.scenario_status()
            refuted = any(ln.get("refutations", 0) > 0 for ln in lines)
            time.sleep(0.2)
        assert refuted, "no refutation after the brief partition healed"
        # nothing was confirmed by the transient: views stay complete
        views = {i: set(cluster.client(i).lsm(i)) for i in range(n)}
        assert views == {i: set(range(n)) for i in range(n)}, views

        # -- kill -9: SUSPECT first (vitals), then the protocol confirm
        victim, observer = 4, 2
        cluster.kill9(victim)
        suspected = False
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            lines = cluster.scenario_status()
            if any(victim in (ln.get("suspects") or [])
                   for ln in lines):
                suspected = True
                break
            time.sleep(0.1)
        assert suspected, "victim never appeared in any suspects vitals"
        cluster.wait_detected(victim, observer, timeout=60.0)
        lines = cluster.scenario_status()
        assert any(ln.get("confirms", 0) > 0 for ln in lines)
        # the detection was logged the normal way (distributed grep)
        hits = []
        for i in range(n):
            if i == victim:
                continue
            hits += cluster.client(i).call(
                "Grep", pattern="detected failure"
            ).get("lines") or []
        assert any(int(ln["subject"]) == victim for ln in hits)
    finally:
        cluster.stop()


class TestLocalHealthFusion:
    """Round 14: the Lifeguard stretch fused into the rr/SWAR fast path
    — flags bit 4 + the carried per-receiver suspect counts — pinned
    bit-exact against the XLA oracle (the per-node reference semantics
    ride the golden fuzz suite's lh config)."""

    @staticmethod
    def _rr_cfg(**over):
        base = dict(
            n=1024, topology="random_arc", fanout=16, arc_align=8,
            remove_broadcast=False, fresh_cooldown=True, t_fail=3,
            t_cooldown=12, view_dtype="int8", hb_dtype="int8",
            merge_kernel="pallas_rr_interpret", merge_block_c=512,
            merge_block_r=128, rr_resident="on", elementwise="swar",
            suspicion=SuspicionParams(t_suspect=2, lh_multiplier=3,
                                      lh_frac=0.25),
        )
        base.update(over)
        return SimConfig(**base)

    def test_rr_lh_no_longer_degrades_and_matches_oracle(self):
        """lh_multiplier > 0 takes the resident-round kernel now (the
        round-11 stripe/XLA degradation is gone) and a mass-suspicion
        crash storm — enough simultaneous suspects to cross lh_frac and
        fire the stretch — is bit-identical to the XLA oracle in every
        state lane, the carry, and the per-round suspicion counters."""
        from gossipfs_tpu.config import fallback_config
        from gossipfs_tpu.core.rounds import _use_rr, run_rounds

        cfg = self._rr_cfg()
        n = cfg.n
        assert _use_rr(cfg, n, n), "lh config must take the rr fast path"
        rounds = 12
        crash = np.zeros((rounds, n), dtype=bool)
        crash[3, 100:500] = True  # ~39% of peers: every survivor stretches
        z = jnp.zeros((rounds, n), dtype=bool)
        ev = RoundEvents(crash=jnp.asarray(crash), leave=z, join=z)
        key = jax.random.PRNGKey(7)
        st_rr, mc_rr, pr_rr = run_rounds(init_state(cfg), cfg, rounds, key,
                                         events=ev, crash_only_events=True)
        oc = fallback_config(cfg)
        assert oc.merge_kernel == "xla"
        st_x, mc_x, pr_x = run_rounds(init_state(oc), oc, rounds, key,
                                      events=ev, crash_only_events=True)
        for name in ("hb", "age", "status", "alive", "hb_base"):
            np.testing.assert_array_equal(
                np.asarray(getattr(st_rr, name)),
                np.asarray(getattr(st_x, name)), err_msg=name)
        for f in mc_rr._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(mc_rr, f)),
                np.asarray(getattr(mc_x, f)), err_msg=f"mc.{f}")
        for f in pr_rr._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(pr_rr, f)),
                np.asarray(getattr(pr_x, f)), err_msg=f"pr.{f}")
        # the stretch actually FIRED: the same storm under lh-off
        # confirms strictly earlier somewhere
        off = self._rr_cfg(suspicion=SuspicionParams(t_suspect=2))
        _, mc_o, _ = run_rounds(init_state(off), off, rounds, key,
                                events=ev, crash_only_events=True)
        assert not np.array_equal(np.asarray(mc_rr.first_detect),
                                  np.asarray(mc_o.first_detect))

    def test_packed_detector_carries_suspect_counts(self):
        """The interactive capacity path (PackedDetector) accepts lh
        configs now and threads the per-receiver suspect counts between
        donated scans exactly like the member counts."""
        from gossipfs_tpu.detector.sim import PackedDetector

        det = PackedDetector(self._rr_cfg())
        assert det._lh and int(np.asarray(det._sus_counts).sum()) == 0
        # counters must clear the hb<=1 grace BEFORE the crash, or the
        # victim dies permanently grace-protected (the zombie-grace
        # rule) and never enters SUSPECT at all
        det.advance(3)
        det.crash(5)
        det.advance(6)
        # node 5 is silent: every live observer's suspect count reflects
        # it once its staleness crosses t_fail
        counts = np.asarray(det._sus_counts)
        assert counts.sum() > 0
        assert 5 not in det.alive_nodes()
