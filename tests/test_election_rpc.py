"""Distributed election end-to-end through the RPC surface (VERDICT #3).

The reference's election is a real multi-node protocol (slave.go:930-1051):
per-node votes over RPC from each node's OWN membership view, a majority
tally, then AssignNewMaster fan-out collecting registries for the metadata
rebuild.  These tests run a CoSim in election="rpc" mode behind a live
gRPC shim and kill the master: the new master must emerge via the
Vote/AssignNewMaster handlers — the central ``cluster._elect`` shortcut is
poisoned to prove it is never taken.
"""

from __future__ import annotations

import pytest

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.cosim import CoSim
from gossipfs_tpu.shim.client import ShimClient
from gossipfs_tpu.shim.service import ShimServer


@pytest.fixture()
def rpc_shim(monkeypatch):
    sim = CoSim(SimConfig(n=10), seed=3, election="rpc")

    def poisoned(self, now=0):  # pragma: no cover - must never run
        raise AssertionError("central _elect used in rpc election mode")

    monkeypatch.setattr(type(sim.cluster), "_elect", poisoned)
    server = ShimServer(sim, port=0).start()
    client = ShimClient(server.address, timeout=30.0)
    yield sim, server, client
    client.close()
    server.stop()


def test_master_crash_elects_via_rpc_surface(rpc_shim):
    sim, server, client = rpc_shim
    assert client.put("meta.txt", b"survives the master")
    client.advance(3)  # counters past the hb grace
    client.crash(0)    # kill the master (the introducer)
    # detection ~t_fail after the crash; the election rides the next Advance
    client.advance(12)
    assert sim.cluster.master_node == 1
    assert not sim.cluster.election_pending
    # the election is visible in the log as the RPC-driven path
    lines = client.grep("Vote/AssignNewMaster")
    assert lines and lines[0]["kind"] == "election"
    # rebuilt metadata still serves the file written under the old master
    assert client.get("meta.txt") == b"survives the master"
    replicas, = [client.ls("meta.txt")]
    assert replicas  # rebuild kept the replica set
    # and the new master accepts writes
    assert client.put("after.txt", b"new regime")


def test_split_vote_stalls_until_majority(rpc_shim):
    """No candidate with a majority -> the election stalls (election_pending
    stays set) and retries; votes through the Vote handler prove the tally
    is doing the gating."""
    sim, server, client = rpc_shim
    n_live = len(sim.cluster.live)
    # a minority of hand-cast votes elects nobody
    for voter in range(n_live // 2):
        reply = client.call("Vote", candidate=7, voter=voter)
        assert not reply["elected"]
    assert sim.cluster.master_node == 0  # unchanged
    # the rest of the cluster joins in: majority crosses, 7 is elected
    reply = client.call("Vote", candidate=7, voter=n_live // 2)
    assert reply["elected"]
    assert sim.cluster.master_node == 7


def test_winner_crash_during_rebuild_aborts_and_reelects(rpc_shim):
    """Master-crash-during-rebuild: the commit is aborted and the next
    Advance re-elects the following candidate."""
    sim, server, client = rpc_shim
    client.advance(3)
    client.crash(0)
    # sabotage: the moment the winner starts collecting registries, it dies
    orig = server.servicer._self_call
    killed = []

    def crash_winner(method, **req):
        if method == "AssignNewMaster" and not killed:
            killed.append(req["master"])
            sim.detector.crash(req["master"])
            sim.detector.advance(1)  # the crash lands before the commit check
        return orig(method, **req)

    server.servicer._self_call = crash_winner
    client.advance(12)
    # first attempt: node 1 won the vote but died mid-rebuild -> aborted
    assert killed == [1]
    assert sim.cluster.master_node != 1 or sim.cluster.election_pending
    # next advances detect 1's death; the re-election installs node 2
    client.advance(12)
    assert sim.cluster.master_node == 2
    assert not sim.cluster.election_pending


def test_local_mode_unchanged():
    """Default CoSim keeps the central election (backwards compatible)."""
    sim = CoSim(SimConfig(n=10), seed=3)
    sim.tick(3)
    sim.detector.crash(0)
    sim.tick(12)
    assert sim.cluster.master_node == 1
