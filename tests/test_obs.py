"""Observability subsystem (gossipfs_tpu/obs/ + tools/timeline.py).

Coverage map:
  * schema lint — every RoundMetrics/MetricsCarry field and every
    deploy/cosim log site maps into the event schema or is explicitly
    unexported (new metrics cannot silently bypass the recorder);
  * decoder oracle — the flight-recorder trace of a churn run
    reproduces ``summarize``'s TTD/FPR EXACTLY from events alone
    (tools/timeline.py --selfcheck, the trace_invariants claim's small
    form), including through the curves ``--trace`` surface;
  * engine parity — same crash, same per-subject lifecycle-kind
    sequence from the tensor sim and the asyncio UDP engine (fast
    lane); the per-process deploy variant rides the slow lane, merging
    the daemons' structured node logs through the analyzer;
  * vitals — the uniform `metrics`/`Vitals` counter set renders
    identically across engines with unknowable fields as n/a, never 0.
"""

from __future__ import annotations

import asyncio
import importlib.util
import io
import json
import pathlib
import re
import time

import pytest

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.obs import schema
from gossipfs_tpu.obs.recorder import FlightRecorder
from gossipfs_tpu.suspicion import SuspicionParams, with_suspicion

REPO = pathlib.Path(__file__).resolve().parents[1]


def _timeline():
    spec = importlib.util.spec_from_file_location(
        "timeline_tool", REPO / "tools" / "timeline.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# schema lint: nothing bypasses the recorder silently
# ---------------------------------------------------------------------------


class TestSchemaLint:
    # Round 15: the two coverage lints migrated onto the gossipfs-lint
    # registry (gossipfs_tpu/analysis/rules_obs.py) — pure-AST forms of
    # the same maps (NamedTuple annotations + literal dicts instead of
    # runtime imports + regexes), with trigger fixtures under
    # tests/fixtures/lint/.  These wrappers keep the enforcement at its
    # historical home on the fast lane; tools/lint.py runs it outside
    # pytest too.

    def test_scan_fields_all_mapped(self):
        """Every RoundMetrics/MetricsCarry field maps to an event kind
        (or sits in the explicit unexported list) — adding a metric
        without deciding its observability story fails here."""
        from gossipfs_tpu.analysis import REGISTRY, RepoIndex

        findings = REGISTRY["obs-scan-coverage"].check(RepoIndex())
        assert not findings, "\n".join(str(f) for f in findings)

    def test_log_sites_all_mapped(self):
        """Every deploy-daemon ``log("<kind>")`` site and every cosim
        ``kind="<kind>"`` site maps into the schema or is listed
        unexported with a reason."""
        from gossipfs_tpu.analysis import REGISTRY, RepoIndex

        findings = REGISTRY["obs-logsite-coverage"].check(RepoIndex())
        assert not findings, "\n".join(str(f) for f in findings)

    def test_lifecycle_and_vitals_shapes(self):
        assert set(schema.LIFECYCLE_KINDS) <= set(schema.EVENT_KINDS)
        doc = {"engine": "udp", "round": 3, "detections": 1}
        line = schema.render_vitals(doc)
        assert "fp_suppressed=n/a" in line and "detections=1" in line

    def test_event_roundtrip(self):
        ev = schema.Event(round=7, observer=2, subject=5, kind="confirm",
                          detail={"false_positive": False})
        assert schema.Event.from_record(ev.to_record()) == ev
        # deploy log rows name the writer as "node"
        assert schema.Event.from_record(
            {"round": 1, "node": 4, "kind": "remove", "subject": 2}
        ).observer == 4


# ---------------------------------------------------------------------------
# decoder oracle: events alone reproduce summarize exactly
# ---------------------------------------------------------------------------


class TestDecoderOracle:
    def test_selfcheck_reproduces_summarize(self):
        """The small form of the trace_invariants claim: record a churn
        run with suspicion, re-derive TTD/FPR from the trace, require
        exact agreement with summarize + the suspect-before-confirm
        invariant."""
        out = _timeline().selfcheck(n=256, rounds=40)
        assert out["ttd_match"], out
        assert out["fpr_match"], out
        assert out["detections_match"] and out["suppression_match"], out
        assert out["suspect_before_confirm"], out
        assert out["ok"], out

    def test_curves_trace_matches_row(self, tmp_path):
        """The bench surface: `curves --trace` writes a stream whose
        analyzer-derived TTD median and FPR equal the committed row's —
        the acceptance criterion's shape at tier-1 size."""
        from gossipfs_tpu.bench.curves import sweep

        trace = tmp_path / "curves_trace.jsonl"
        out = sweep(ns=(256,), rounds=30, trace=str(trace))
        (row,) = out["rows"]
        tl = _timeline()
        headers, events = tl.merge([str(trace)])
        doc = tl.analyze(headers, events)
        assert doc["ttd_first_median"] == row["ttd_first_median"]
        assert doc["false_positive_rate"] == row["false_positive_rate"]
        assert doc["detected"] == row["detected"]
        assert doc["tracked_crashes"] == row["tracked_crashes"]

    def test_bulk_recorder_matches_drained_events(self):
        """advance_bulk decodes its scan into the recorder; the confirm
        events carry the same (round, observer, subject) triples the
        DetectionEvent stream reports."""
        from gossipfs_tpu.detector.sim import SimDetector

        cfg = SimConfig(n=32, topology="random", fanout=5,
                        remove_broadcast=False, fresh_cooldown=True,
                        t_cooldown=12, merge_kernel="xla")
        det = SimDetector(cfg, seed=0)
        rec = FlightRecorder(source="sim", n=32)
        det.attach_recorder(rec)
        det.advance_bulk(2)  # past the hb<=1 detection grace
        det.crash(3)
        det.crash(17)
        det.advance_bulk(20)
        events = det.drain_events()  # resolves the scans + the decode
        assert {e.subject for e in events} == {3, 17}
        confirms = {(e.round, e.observer, e.subject)
                    for e in rec.events if e.kind == "confirm"}
        assert {(e.round, e.observer, e.subject) for e in events} == confirms
        ticks = [e for e in rec.events if e.kind == "round_tick"]
        assert len(ticks) == 22
        # the bulk trace carries the ground-truth verb rows too, so the
        # analyzer derives TTD from it exactly like an interactive trace
        crashes = {e.subject for e in rec.events if e.kind == "crash"}
        assert crashes == {3, 17}
        tl = _timeline()
        doc = tl.analyze([rec.header], rec.events)
        assert doc["tracked_crashes"] == 2
        assert all(v >= 0 for v in doc["ttd_first"].values()), doc

    def test_decode_masks_pad_subjects(self):
        """Padded frontier runs: permanently-dead alignment pads
        'converge' at the first round — they must not export phantom
        remove rows (they were never members)."""
        import jax

        from gossipfs_tpu.core.rounds import run_rounds
        from gossipfs_tpu.core.state import init_state
        from gossipfs_tpu.obs.recorder import decode_scan
        import numpy as np

        n_pad, n_eff = 64, 48
        cfg = SimConfig(n=n_pad, topology="random", fanout=5,
                        remove_broadcast=False, fresh_cooldown=True,
                        t_cooldown=12, merge_kernel="xla")
        mask = np.arange(n_pad) < n_eff
        final, carry, per_round = run_rounds(
            init_state(cfg, member_mask=mask), cfg, 10,
            jax.random.PRNGKey(0))
        evs = decode_scan(per_round, carry, n=n_pad, alive=final.alive,
                          n_effective=n_eff)
        assert all(e.subject < n_eff for e in evs if e.subject >= 0), [
            e for e in evs if e.subject >= n_eff]

    def test_no_refute_on_leave(self):
        """A suspected subject that LEAVEs departs SUSPECT without any
        evidence of life — the recorder must not invent a refute row
        (it would contradict the round_tick refutation counters)."""
        from gossipfs_tpu.detector.sim import SimDetector
        from gossipfs_tpu.scenarios import split_halves

        n = 10
        cfg = with_suspicion(
            SimConfig(n=n, remove_broadcast=False, fresh_cooldown=True,
                      t_cooldown=6, t_fail=3),
            SuspicionParams(t_suspect=12),
        )
        det = SimDetector(cfg, seed=0)
        rec = FlightRecorder(source="sim", n=n)
        det.attach_recorder(rec)
        det.load_scenario(split_halves(n, start=2, end=40))
        det.advance(10)  # suspicions accumulate, window far from confirm
        assert any(e.kind == "suspect" for e in rec.events)
        victim = next(e.subject for e in rec.events if e.kind == "suspect")
        det.clear_scenario()
        det.leave(victim)
        det.advance(1)
        kinds = rec.kinds(subject=victim)
        assert "leave" in kinds
        assert "refute" not in kinds, kinds


# ---------------------------------------------------------------------------
# engine parity: one crash, one lifecycle, three engines
# ---------------------------------------------------------------------------


def _sus_cfg(n: int) -> SimConfig:
    return with_suspicion(
        SimConfig(n=n, remove_broadcast=False, fresh_cooldown=True,
                  t_cooldown=6),
        SuspicionParams(t_suspect=3),
    )


class TestEngineTraceParity:
    LIFECYCLE = ["crash", "hb_freeze", "suspect", "confirm", "remove"]

    def _offsets(self, events, subject):
        rounds = {}
        for e in sorted(events, key=lambda ev: ev.round):
            if e.subject == subject and e.kind not in rounds:
                rounds[e.kind] = e.round
        r0 = rounds["crash"]
        return {k: r - r0 for k, r in rounds.items()}

    def test_sim_vs_udp_kind_sequences(self):
        """Same crash under the same suspicion policy: both engines emit
        the identical deduped per-subject kind sequence, with round
        offsets agreeing within socket-scheduling jitter (the sim's are
        deterministic; the UDP engine ticks on real timers)."""
        tl = _timeline()
        n, victim = 10, 6

        # -- tensor sim (interactive recorder backend)
        from gossipfs_tpu.detector.sim import SimDetector

        det = SimDetector(_sus_cfg(n), seed=0)
        sim_rec = FlightRecorder(source="sim", n=n)
        det.attach_recorder(sim_rec)
        det.advance(2)  # past the initial grace
        det.crash(victim)
        det.advance(25)
        sim_seq = tl.kind_sequence(sim_rec.events, victim)
        sim_off = self._offsets(sim_rec.events, victim)

        # -- asyncio UDP engine (seam-hook backend)
        from gossipfs_tpu.detector.udp import UdpCluster

        async def udp_run():
            c = UdpCluster(n=n, base_port=24100, period=0.05,
                           fresh_cooldown=True,
                           suspicion=SuspicionParams(t_suspect=3))
            rec = FlightRecorder(source="udp", n=n)
            c.attach_recorder(rec)
            try:
                await c.start_all()
                await c.run(4)
                c.crash(victim)
                await c.run(30)
                return rec
            finally:
                c.stop_all()

        udp_rec = asyncio.run(udp_run())
        udp_seq = tl.kind_sequence(udp_rec.events, victim)
        udp_off = self._offsets(udp_rec.events, victim)

        assert sim_seq == self.LIFECYCLE, sim_seq
        assert udp_seq == self.LIFECYCLE, udp_seq
        # offsets: identical kinds, rounds within real-socket jitter
        for kind in ("suspect", "confirm"):
            assert abs(sim_off[kind] - udp_off[kind]) <= 3, (
                kind, sim_off, udp_off)
        # the causal order is strict in both
        for off in (sim_off, udp_off):
            assert 0 < off["suspect"] < off["confirm"] <= off["remove"]

    def test_sim_refute_on_heal(self):
        """A partition that heals inside the SUSPECT window leaves a
        suspect -> refute trace (and no confirm) for the cut-off side."""
        from gossipfs_tpu.detector.sim import SimDetector
        from gossipfs_tpu.scenarios import split_halves

        n = 10
        cfg = with_suspicion(
            SimConfig(n=n, remove_broadcast=False, fresh_cooldown=True,
                      t_cooldown=6, t_fail=3),
            SuspicionParams(t_suspect=8),
        )
        det = SimDetector(cfg, seed=0)
        rec = FlightRecorder(source="sim", n=n)
        det.attach_recorder(rec)
        det.load_scenario(split_halves(n, start=3, end=10))
        det.advance(25)
        tl = _timeline()
        kinds = rec.kinds()
        assert "scenario_arm" in kinds
        assert "suspect" in kinds and "refute" in kinds
        assert "confirm" not in kinds
        # every suspected subject's sequence ends in refute, not confirm
        for subj in {e.subject for e in rec.events if e.kind == "suspect"}:
            seq = tl.kind_sequence(rec.events, subj)
            assert seq == ["suspect", "refute"], (subj, seq)


# ---------------------------------------------------------------------------
# vitals: one counter set, n/a for the unknowable
# ---------------------------------------------------------------------------


class TestVitals:
    def test_sim_vitals_and_cli_metrics_verb(self):
        from gossipfs_tpu.cosim import CoSim
        from gossipfs_tpu.shim import cli

        sim = CoSim(SimConfig(n=8, remove_broadcast=False,
                              fresh_cooldown=True), seed=0)
        sim.tick(2)
        doc = sim.vitals()
        assert doc["engine"] == "sim" and doc["n_alive"] == 8
        out = io.StringIO()
        cli.dispatch(sim, "metrics", out=out)
        line = out.getvalue()
        assert "engine=sim" in line and "n_alive=8" in line
        # suspicion not armed: its counters are absent -> n/a, never 0
        assert "fp_suppressed=n/a" in line

    def test_sim_vitals_with_suspicion_counts(self):
        from gossipfs_tpu.cosim import CoSim
        from gossipfs_tpu.shim import cli

        sim = CoSim(_sus_cfg(10), seed=0)
        sim.tick(1)
        out = io.StringIO()
        cli.dispatch(sim, "metrics", out=out)
        # armed: the sim-only field is a real number now
        assert re.search(r"fp_suppressed=\d+", out.getvalue())

    def test_shim_vitals_rpc(self):
        from gossipfs_tpu.cosim import CoSim
        from gossipfs_tpu.shim.service import ShimServicer
        from gossipfs_tpu.shim.wire import METHOD_TYPES

        assert "Vitals" in METHOD_TYPES
        sim = CoSim(SimConfig(n=8, remove_broadcast=False,
                              fresh_cooldown=True), seed=0)
        servicer = ShimServicer(sim)
        (line,) = servicer.Vitals({}, None)["lines"]
        assert line["engine"] == "sim" and line["round"] == 0

    def test_monitor_vitals_absent_is_na_never_zero(self):
        """The round-13 counter: `invariant_violations` appears ONLY
        when a streaming monitor rides the attached recorder.  Without
        one, the CLI `metrics` and `traffic status` verbs render n/a —
        a fabricated clean 0 would claim a health check that never
        ran."""
        from gossipfs_tpu.cosim import CoSim
        from gossipfs_tpu.shim import cli

        assert "invariant_violations" in schema.VITALS_FIELDS
        sim = CoSim(SimConfig(n=8, remove_broadcast=False,
                              fresh_cooldown=True), seed=0)
        sim.tick(2)
        assert "invariant_violations" not in sim.vitals()
        out = io.StringIO()
        cli.dispatch(sim, "metrics", out=out)
        assert "invariant_violations=n/a" in out.getvalue()
        out = io.StringIO()
        cli.dispatch(sim, "traffic status", out=out)
        assert "invariant_violations=n/a" in out.getvalue()

    def test_monitor_vitals_live_when_attached(self):
        from gossipfs_tpu.cosim import CoSim
        from gossipfs_tpu.obs.monitor import MonitorRecorder
        from gossipfs_tpu.shim import cli

        sim = CoSim(SimConfig(n=8, remove_broadcast=False,
                              fresh_cooldown=True), seed=0)
        sim.attach_recorder(MonitorRecorder(source="sim", n=8))
        sim.tick(2)
        assert sim.vitals()["invariant_violations"] == 0
        out = io.StringIO()
        cli.dispatch(sim, "metrics", out=out)
        assert re.search(r"invariant_violations=\d+", out.getvalue())
        out = io.StringIO()
        cli.dispatch(sim, "traffic status", out=out)
        assert re.search(r"invariant_violations=\d+", out.getvalue())

    def test_udp_vitals_omit_sim_only_fields(self):
        from gossipfs_tpu.detector.udp import UdpCluster

        async def run():
            c = UdpCluster(n=5, base_port=24300, period=0.05,
                           fresh_cooldown=True)
            try:
                await c.start_all()
                await c.run(4)  # past the hb<=1 detection grace
                c.crash(4)
                await c.run(12)
                return c.vitals()
            finally:
                c.stop_all()

        doc = asyncio.run(run())
        assert doc["engine"] == "udp"
        assert doc["detections"] >= 1
        # ground truth the socket engine DOES have in-process:
        assert doc["false_positives"] == 0
        # the per-refute ground truth it does not:
        assert "fp_suppressed" not in doc
        assert "fp_suppressed=n/a" in schema.render_vitals(doc)


# ---------------------------------------------------------------------------
# streaming invariant monitor (obs/monitor.py) — the online health plane
# ---------------------------------------------------------------------------


def _tick(r, fp=0, alive=32, sus=None):
    detail = {"n_alive": alive, "true_detections": 0,
              "false_positives": fp}
    if sus is not None:
        detail.update(suspects_entered=sus, refutations=0,
                      fp_suppressed=0)
    return schema.Event(round=r, observer=-1, subject=-1,
                        kind="round_tick", detail=detail)


class TestStreamMonitor:
    """Invariant rows on synthetic streams (deterministic, jax-free) +
    the parity oracle and the inline recorder attachment."""

    def test_parity_claim_small_form(self):
        """The monitor_parity claim at tier-1 size: the streaming
        estimators equal timeline.py's post-hoc derivation exactly on
        the selfcheck stream, with zero violations on the healthy run."""
        out = _timeline().selfcheck(n=256, rounds=40, monitor=True)
        assert out["monitor_parity"], out.get("monitor_mismatches")
        assert out["monitor_violations"] == 0
        assert out["ok"], out

    def test_no_confirm_without_suspect(self):
        from gossipfs_tpu.obs.monitor import StreamMonitor

        mon = StreamMonitor(n=32)
        viol = mon.feed([
            _tick(0, sus=0),
            schema.Event(round=2, observer=-1, subject=5, kind="suspect"),
            schema.Event(round=4, observer=1, subject=5, kind="confirm"),
            # subject 9 confirms with NO preceding suspect
            schema.Event(round=5, observer=2, subject=9, kind="confirm"),
        ])
        assert [v.detail["invariant"] for v in viol] == [
            "no_confirm_without_suspect"]
        assert viol[0].subject == 9
        # the post-hoc mirror agrees
        assert mon.summary()["suspect_before_confirm"] is False

    def test_no_acked_write_lost_end_of_stream(self):
        from gossipfs_tpu.obs.monitor import StreamMonitor

        def put(r, name, reps):
            return schema.Event(round=r, observer=0, subject=-1,
                                kind="replica_put",
                                detail={"file": name, "version": 1,
                                        "replicas": reps})

        mon = StreamMonitor(n=8)
        mon.feed([
            put(1, "a.txt", [1, 2]),
            put(1, "b.txt", [3]),
            schema.Event(round=3, observer=-1, subject=3, kind="crash"),
        ])
        viol = mon.finish()
        assert [v.detail["invariant"] for v in viol] == [
            "no_acked_write_lost"]
        assert viol[0].detail["files"] == ["b.txt"]
        d = mon.summary()["durability"]
        assert d["lost"] == 1 and d["acked_writes"] == 2
        # a rejoin of the only holder heals the ledger
        mon2 = StreamMonitor(n=8)
        mon2.feed([
            put(1, "b.txt", [3]),
            schema.Event(round=3, observer=-1, subject=3, kind="crash"),
            schema.Event(round=6, observer=-1, subject=3, kind="join"),
        ])
        assert mon2.finish() == []

    def test_reconverge_bound(self):
        from gossipfs_tpu.obs.monitor import MonitorParams, StreamMonitor

        base = [
            schema.Event(round=2, observer=-1, subject=4, kind="crash"),
            *[_tick(r) for r in range(20)],
        ]
        # removed in time: clean
        mon = StreamMonitor(params=MonitorParams(reconverge_bound=8), n=16)
        mon.feed(base + [schema.Event(round=9, observer=-1, subject=4,
                                      kind="remove")])
        assert mon.finish() == [] and not mon.violations
        # never removed, horizon past the deadline: flagged at finish
        mon2 = StreamMonitor(params=MonitorParams(reconverge_bound=8), n=16)
        mon2.feed(base)
        viol = mon2.finish()
        assert [v.detail["invariant"] for v in viol] == ["reconverge_bound"]
        assert viol[0].subject == 4 and viol[0].detail["removed"] is False
        # a scenario_clear after the crash re-clocks the deadline
        mon3 = StreamMonitor(params=MonitorParams(reconverge_bound=8), n=16)
        mon3.feed(base + [
            schema.Event(round=14, observer=-1, subject=-1,
                         kind="scenario_clear"),
            schema.Event(round=18, observer=-1, subject=4, kind="remove"),
        ])
        assert mon3.finish() == [] and not mon3.violations

    def test_reconverge_episodes_and_duplicate_removes(self):
        """A rejoin + re-crash re-clocks the reconvergence deadline (a
        prompt second removal is clean even though the FIRST crash's
        deadline is long gone), and repeated per-observer remove rows
        evaluate the episode once — no duplicate violations."""
        from gossipfs_tpu.obs.monitor import MonitorParams, StreamMonitor

        mon = StreamMonitor(params=MonitorParams(reconverge_bound=8), n=16)
        mon.feed([
            schema.Event(round=2, observer=-1, subject=4, kind="crash"),
            *[_tick(r) for r in range(40)],
            schema.Event(round=20, observer=-1, subject=4, kind="remove"),
            schema.Event(round=20, observer=1, subject=4, kind="remove"),
            schema.Event(round=21, observer=2, subject=4, kind="remove"),
            schema.Event(round=25, observer=-1, subject=4, kind="join"),
            schema.Event(round=30, observer=-1, subject=4, kind="crash"),
            schema.Event(round=36, observer=-1, subject=4, kind="remove"),
        ])
        mon.finish()
        # exactly ONE violation: the first episode's late removal
        # (remove@20 > crash@2 + 8); the re-crash episode's remove@36
        # is inside crash@30 + 8, and the repeat rows add nothing
        assert len(mon.violations) == 1
        v = mon.violations[0]
        assert v.detail["crash_round"] == 2 and v.round == 20
        # analyze-parity untouched: crash_rounds keeps the FIRST crash
        assert mon.crash_rounds == {4: 2}

    def test_durability_gate_matches_analyze(self):
        """A repair-only tail (no replica_put/client_op) must not grow
        a durability doc the post-hoc analyzer omits — the parity
        oracle's gates are identical by construction."""
        from gossipfs_tpu.obs.monitor import StreamMonitor, estimator_parity

        events = [
            _tick(0),
            schema.Event(round=1, observer=0, subject=-1,
                         kind="replica_repair",
                         detail={"file": "a", "version": 1,
                                 "targets": [2]}),
        ]
        mon = StreamMonitor()  # n rides the header on real streams;
        mon.feed(events)       # none here, matching analyze's view
        mon.finish()
        assert "durability" not in mon.summary()
        doc = _timeline().analyze([], events)
        assert estimator_parity(doc, mon.summary())["ok"]

    def test_fpr_storm_edge_triggered(self):
        from gossipfs_tpu.obs.monitor import MonitorParams, StreamMonitor

        mon = StreamMonitor(
            params=MonitorParams(fpr_threshold=1e-3, fpr_window=4), n=32)
        viol = mon.feed([_tick(r) for r in range(6)]
                        + [_tick(6, fp=8), _tick(7, fp=8)]   # the storm
                        + [_tick(r) for r in range(8, 14)]   # recovery
                        + [_tick(14, fp=9)])                 # second storm
        kinds = [v.detail["invariant"] for v in viol]
        # edge-triggered: one violation per storm ENTRY, not per round
        assert kinds == ["fpr_storm", "fpr_storm"]
        assert mon.storm_rounds >= 3
        assert mon.worst_window_fpr > 1e-3

    def test_monitor_recorder_inline_and_replay_idempotent(self, tmp_path):
        """MonitorRecorder rides attach_recorder on the interactive sim:
        the violation lands IN the written stream; re-analyzing the file
        surfaces it, and a fresh monitor over the same file re-derives
        (not double-counts) it."""
        from gossipfs_tpu.detector.sim import SimDetector
        from gossipfs_tpu.obs.monitor import (
            MonitorParams,
            MonitorRecorder,
            StreamMonitor,
        )
        from gossipfs_tpu.scenarios import FaultScenario, Flapping

        n = 24
        cfg = SimConfig(n=n, remove_broadcast=False, fresh_cooldown=True,
                        t_cooldown=6, t_fail=3, merge_kernel="xla")
        det = SimDetector(cfg, seed=0)
        path = tmp_path / "flap_trace.jsonl"
        rec = MonitorRecorder(
            path, source="sim", n=n,
            params=MonitorParams(fpr_threshold=1e-3, fpr_window=6))
        det.attach_recorder(rec)
        det.load_scenario(FaultScenario(
            name="flap", n=n,
            flapping=(Flapping(start=2, end=40, up=2, down=5,
                               nodes=(3, 4)),)))
        det.advance(40)
        rec.close()
        inline = [e for e in rec.events
                  if e.kind == "invariant_violation"]
        assert inline and inline[0].detail["invariant"] == "fpr_storm"
        # the written artifact carries its own verdict
        tl = _timeline()
        headers, events = tl.merge([str(path)])
        doc = tl.analyze(headers, events)
        assert doc["invariant_violations"] == len(inline)
        # replay idempotence: a fresh monitor over the monitored stream
        # re-derives the same storm count from the raw rows
        mon2 = StreamMonitor(
            params=MonitorParams(fpr_threshold=1e-3, fpr_window=6))
        mon2.feed_jsonl(path)
        mon2.finish()
        assert len(mon2.violations) == len(inline)

    def test_bulk_decode_feeds_monitor(self):
        """advance_bulk's post-scan decode flows through the inline
        monitor exactly like interactive rounds (the bulk attachment
        surface)."""
        from gossipfs_tpu.detector.sim import SimDetector
        from gossipfs_tpu.obs.monitor import MonitorRecorder

        cfg = SimConfig(n=32, topology="random", fanout=5,
                        remove_broadcast=False, fresh_cooldown=True,
                        t_cooldown=12, merge_kernel="xla")
        det = SimDetector(cfg, seed=0)
        rec = MonitorRecorder(source="sim", n=32)
        det.attach_recorder(rec)
        det.advance_bulk(2)
        det.crash(3)
        det.advance_bulk(20)
        det.drain_events()
        rec.finish()
        mon = rec.monitor
        assert mon.rounds == 22
        assert mon.crash_rounds == {3: 2}
        assert not mon.violations
        assert mon.summary()["ttd_converged"][3] >= 0

    def test_deploy_log_tail_mode(self, tmp_path):
        """feed_jsonl over a deploy-style node log (no header, `node`
        names the observer): the file-attachment mode for engines the
        monitor cannot sit inside."""
        from gossipfs_tpu.obs.monitor import StreamMonitor

        p = tmp_path / "node1.log"
        p.write_text(
            json.dumps({"round": 1, "node": 1, "kind": "suspect",
                        "subject": 3}) + "\n"
            + "free text line survives\n"
            + json.dumps({"round": 3, "node": 1, "kind": "confirm",
                          "subject": 3}) + "\n"
            + json.dumps({"round": 4, "node": 1, "kind": "confirm",
                          "subject": 5}) + "\n")
        from gossipfs_tpu.obs.monitor import MonitorParams

        mon = StreamMonitor(
            params=MonitorParams(expect_suspicion=True), n=5)
        viol = mon.feed_jsonl(p)
        assert [v.subject for v in viol] == [5]


# ---------------------------------------------------------------------------
# profiler-artifact headers (ROUNDPROF convention) + profile hook
# ---------------------------------------------------------------------------


class TestProfilerArtifacts:
    def test_emitters_stamp_schema_header(self):
        """bench/roundprof.py and tools/stub_bisect.py must emit the
        self-describing header row (satellite: old and new ROUNDPROF
        artifacts distinguishable by their first line)."""
        for rel in ("gossipfs_tpu/bench/roundprof.py",
                    "tools/stub_bisect.py"):
            assert "ROUNDPROF_SCHEMA" in (REPO / rel).read_text(), rel

    def test_timeline_ingests_roundprof_stream(self, tmp_path):
        p = tmp_path / "ROUNDPROF_test.jsonl"
        p.write_text(
            json.dumps({"schema": schema.ROUNDPROF_SCHEMA,
                        "tool": "roundprof", "n": 1024}) + "\n"
            + json.dumps({"config": "xla", "ms_per_round": 9.5,
                          "elementwise": "lanes"}) + "\n"
            + json.dumps({"config": "rr", "ms_per_round": 4.2,
                          "elementwise": "swar"}) + "\n"
        )
        doc = _timeline().summarize_roundprof(str(p))
        assert doc["rows"] == 2
        assert doc["fastest"]["config"] == "rr"

    def test_maybe_xprof_disabled_is_noop(self):
        from gossipfs_tpu.obs.profile import maybe_xprof

        with maybe_xprof(None):
            pass  # no jax import, no trace dir, no error


# ---------------------------------------------------------------------------
# recorder overhead: the device program is identical with recording on
# ---------------------------------------------------------------------------


class TestRecorderOffHotPath:
    def test_decode_is_post_scan_only(self):
        """The acceptance criterion's structural half: run_rounds with
        and without a --trace consumer lower to the SAME jaxpr-level
        call — recording takes no config field, passes no operand, and
        decodes only what summarize already transfers.  Measured: the
        decode of a 40-round N=256 run is host-side milliseconds."""
        import jax

        from gossipfs_tpu.bench.run import tracked_crash_events
        from gossipfs_tpu.core.rounds import run_rounds
        from gossipfs_tpu.core.state import init_state
        from gossipfs_tpu.obs.recorder import decode_scan

        cfg = SimConfig(n=256, topology="random", fanout=8,
                        remove_broadcast=False, fresh_cooldown=True,
                        t_cooldown=12, merge_kernel="xla")
        events, crash_rounds, churn_ok = tracked_crash_events(cfg, 40, 4, 5)
        final, carry, per_round = run_rounds(
            init_state(cfg), cfg, 40, jax.random.PRNGKey(0),
            events=events, crash_rate=0.01, churn_ok=churn_ok,
            crash_only_events=True,
        )
        jax.block_until_ready(carry)
        t0 = time.perf_counter()
        evs = decode_scan(per_round, carry, n=256,
                          crash_rounds=crash_rounds, alive=final.alive)
        decode_s = time.perf_counter() - t0
        assert evs and decode_s < 1.0  # host-side, far under 2% of any run
        # the round_tick rows cover the whole horizon (FPR denominator)
        assert sum(1 for e in evs if e.kind == "round_tick") == 40


# ---------------------------------------------------------------------------
# deploy variant (slow lane): structured node logs ARE the trace
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_deploy_trace_and_vitals(tmp_path):
    """The per-process deployment's observability end to end: the
    daemons' structured JSONL logs merge through tools/timeline.py into
    the victim's suspect -> confirm lifecycle, and the Vitals RPC serves
    the uniform counter rows with ground-truth fields absent (n/a)."""
    from gossipfs_tpu.deploy.launcher import Cluster

    n = 5
    cluster = Cluster(n, period=0.1, root=str(tmp_path), t_fail=5)
    try:
        cluster.start(timeout=90.0)
        acked = cluster.load_suspicion(SuspicionParams(t_suspect=10))
        assert set(acked) == set(range(n))
        victim, observer = 3, 1
        cluster.kill9(victim)
        cluster.wait_detected(victim, observer, timeout=60.0)

        # vitals: every survivor serves the uniform row; no ground-truth
        # fields fabricated by the per-process engine
        lines = cluster.vitals()
        assert len(lines) == n - 1
        assert all(ln["engine"] == "deploy" for ln in lines)
        assert any(ln.get("detections", 0) >= 1 for ln in lines)
        assert all("n_alive" not in ln and "false_positives" not in ln
                   for ln in lines)
        rendered = schema.render_vitals(lines[0])
        assert "n_alive=n/a" in rendered and "fp_suppressed=n/a" in rendered

        # the node logs are schema streams: merge + reconstruct
        tl = _timeline()
        logs = sorted(str(p) for p in pathlib.Path(cluster.root)
                      .glob("node*.log"))
        headers, events = tl.merge(logs)
        assert any(h.get("schema") == schema.SCHEMA for h in headers)
        seq = tl.kind_sequence(events, victim)
        assert "confirm" in seq, seq
        assert "suspect" in seq, seq
        assert seq.index("suspect") < seq.index("confirm"), seq
    finally:
        cluster.stop()
