"""Async membership snapshots (SURVEY §7.4's async boundary).

A host callback inside the scan streams the membership view to a buffer
every k rounds; readers (e.g. the gRPC shim's thread) get a consistent
point-in-time view without blocking on in-flight device futures.
"""

import jax
import jax.numpy as jnp
import numpy as np

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.core.rounds import run_rounds
from gossipfs_tpu.core.state import MEMBER, init_state
from gossipfs_tpu.utils.snapshot import SnapshotBuffer

KEY = jax.random.PRNGKey(21)


def test_snapshots_stream_at_cadence_and_match_final():
    cfg = SimConfig(n=128, topology="random", fanout=6,
                    merge_kernel="pallas_interpret")
    buf = SnapshotBuffer(keep_history=True)
    final, _, _ = run_rounds(
        init_state(cfg), cfg, 25, KEY, crash_rate=0.05, snapshot=(buf, 5)
    )
    jax.block_until_ready(final.status)
    assert [s.round for s in buf.history] == [5, 10, 15, 20, 25]
    last = buf.latest()
    assert last.round == 25
    # the round-25 snapshot IS the final state (blocked layout unfolded)
    np.testing.assert_array_equal(last.status, np.asarray(final.status))
    np.testing.assert_array_equal(last.alive, np.asarray(final.alive))


def test_detector_advance_bulk_with_snapshots():
    """SimDetector.advance_bulk: one compiled scan, pending verbs applied
    on the first round, snapshots streaming at cadence."""
    from gossipfs_tpu.detector.sim import SimDetector

    cfg = SimConfig(n=64, topology="random", fanout=6)
    det = SimDetector(cfg)
    det.advance(3)  # let counters pass the hb grace before crashing anyone
    det.crash(7)
    buf = det.advance_bulk(20, snapshot_every=5)
    jax.block_until_ready(det.state.status)
    assert int(det.state.round) == 23
    snap = buf.latest()
    assert snap.round == 20
    assert not snap.alive[7]
    assert 7 not in snap.membership(0)
    # bulk advancement synthesizes cluster-level detection events
    events = [e for e in det.drain_events() if e.subject == 7]
    assert events and events[0].observer == -1
    assert 7 <= events[0].round <= 11  # crash ~round 4 + t_fail + spread
    assert not events[0].false_positive
    # bulk path agrees with the per-round path on the final view
    det2 = SimDetector(cfg)
    det2.advance(3)
    det2.crash(7)
    det2.advance(20)
    np.testing.assert_array_equal(
        np.asarray(det.state.status), np.asarray(det2.state.status)
    )


def test_snapshot_membership_view_consistent():
    cfg = SimConfig(n=64, topology="random", fanout=6)
    buf = SnapshotBuffer()
    crash = np.zeros((20, cfg.n), dtype=bool)
    crash[2, 7] = True
    z = jnp.zeros((20, cfg.n), dtype=bool)
    from gossipfs_tpu.core.state import RoundEvents

    ev = RoundEvents(crash=jnp.asarray(crash), leave=z, join=z)
    final, _, _ = run_rounds(
        init_state(cfg), cfg, 20, KEY, events=ev, snapshot=(buf, 20)
    )
    jax.block_until_ready(final.status)
    snap = buf.latest()
    # every live observer has dropped the crashed node by round 20
    for obs in range(cfg.n):
        if snap.alive[obs] and obs != 7:
            assert 7 not in snap.membership(obs)
    # and membership() agrees with the raw status lane
    assert snap.membership(0) == np.nonzero(
        np.asarray(final.status)[0] == int(MEMBER)
    )[0].tolist()
