"""Async membership snapshots (SURVEY §7.4's async boundary).

The detector's bulk path scans the horizon in compiled chunks pipelined
from a background thread; a Snapshot is published as each chunk completes.
No host callbacks are involved (they cannot cross a remote-PJRT TPU
tunnel), and chunking with a threaded metrics carry is bit-identical to
one long scan.
"""

import jax
import jax.numpy as jnp
import numpy as np

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.core.rounds import run_rounds
from gossipfs_tpu.core.state import MEMBER, RoundEvents, init_state
from gossipfs_tpu.detector.sim import SimDetector

KEY = jax.random.PRNGKey(21)


def test_chunked_scan_bit_identical_to_monolithic():
    """run_rounds with a threaded mcarry0 == one long scan, exactly."""
    cfg = SimConfig(n=128, topology="random", fanout=6)
    crash = np.zeros((24, cfg.n), dtype=bool)
    crash[2, 7] = True
    crash[9, 33] = True
    z = jnp.zeros((24, cfg.n), dtype=bool)
    ev = RoundEvents(crash=jnp.asarray(crash), leave=z, join=z)

    mono_state, mono_mc, _ = run_rounds(init_state(cfg), cfg, 24, KEY, events=ev)

    st = init_state(cfg)
    mc = None
    for off in range(0, 24, 8):
        chunk = RoundEvents(
            crash=ev.crash[off:off + 8], leave=ev.leave[off:off + 8],
            join=ev.join[off:off + 8],
        )
        st, mc, _ = run_rounds(st, cfg, 8, KEY, events=chunk, mcarry0=mc)

    np.testing.assert_array_equal(np.asarray(st.status), np.asarray(mono_state.status))
    np.testing.assert_array_equal(np.asarray(st.hb), np.asarray(mono_state.hb))
    np.testing.assert_array_equal(
        np.asarray(mc.first_detect), np.asarray(mono_mc.first_detect)
    )
    np.testing.assert_array_equal(
        np.asarray(mc.first_observer), np.asarray(mono_mc.first_observer)
    )
    np.testing.assert_array_equal(
        np.asarray(mc.converged), np.asarray(mono_mc.converged)
    )


def test_detector_advance_bulk_with_snapshots():
    """SimDetector.advance_bulk: pending verbs applied on the first round,
    snapshots streaming at chunk cadence, final view == per-round path."""
    cfg = SimConfig(n=64, topology="random", fanout=6)
    det = SimDetector(cfg)
    det.advance(3)  # let counters pass the hb grace before crashing anyone
    det.crash(7)
    buf = det.advance_bulk(20, snapshot_every=5)
    det._join_bulk()
    assert int(det.state.round) == 23
    snap = buf.latest()
    assert snap.round == 23
    assert not snap.alive[7]
    assert 7 not in snap.membership(0)
    # bulk advancement synthesizes per-subject detection events with a REAL
    # observer id (the lowest-index detector of the first firing round)
    events = [e for e in det.drain_events() if e.subject == 7]
    assert events and events[0].observer >= 0
    assert 7 <= events[0].round <= 11  # crash ~round 4 + t_fail + spread
    assert not events[0].false_positive
    # bulk path agrees with the per-round path on the final view AND on the
    # first detection event per subject (VERDICT #9's done criterion)
    det2 = SimDetector(cfg)
    det2.advance(3)
    det2.crash(7)
    det2.advance(20)
    np.testing.assert_array_equal(
        np.asarray(det.state.status), np.asarray(det2.state.status)
    )
    ev2 = [e for e in det2.drain_events() if e.subject == 7]
    assert ev2
    assert events[0].round == ev2[0].round
    assert events[0].observer == min(e.observer for e in ev2 if e.round == ev2[0].round)


def test_advance_bulk_reuses_compiled_scan():
    """Repeat AdvanceBulk calls must not grow the jit cache (the round-1
    advisor's recompile finding): the cache key no longer contains any
    per-call object."""
    cfg = SimConfig(n=64, topology="random", fanout=6)
    det = SimDetector(cfg)
    det.advance_bulk(10, snapshot_every=5)
    det._join_bulk()
    size_after_first = run_rounds._cache_size()
    for _ in range(3):
        det.advance_bulk(10, snapshot_every=5)
        det._join_bulk()
    assert run_rounds._cache_size() == size_after_first


def test_snapshot_membership_view_consistent():
    cfg = SimConfig(n=64, topology="random", fanout=6)
    det = SimDetector(cfg)
    det.advance(3)
    det.crash(7)
    buf = det.advance_bulk(20, snapshot_every=20)
    det._join_bulk()
    snap = buf.latest()
    # every live observer has dropped the crashed node by round 23
    for obs in range(cfg.n):
        if snap.alive[obs] and obs != 7:
            assert 7 not in snap.membership(obs)
    # and membership() agrees with the raw status lane
    assert snap.membership(0) == np.nonzero(
        np.asarray(det.state.status)[0] == int(MEMBER)
    )[0].tolist()
    assert snap.status.shape == (cfg.n, cfg.n)


def test_snapshots_appear_while_scan_runs():
    """The buffer fills chunk by chunk: an early snapshot is observable
    before the full horizon resolves (polling, since timing is host-load
    dependent — the invariant is monotone progress, not exact cadence)."""
    cfg = SimConfig(n=128, topology="random", fanout=7)
    det = SimDetector(cfg)
    import time

    buf = det.advance_bulk(40, snapshot_every=10)
    seen = set()
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        s = buf.latest()
        if s is not None:
            seen.add(s.round)
            if s.round >= 40:
                break
        time.sleep(0.002)
    det._join_bulk()
    assert 40 in seen
    final = buf.latest()
    assert final.round == 40
    np.testing.assert_array_equal(final.alive, np.asarray(det.state.alive))
